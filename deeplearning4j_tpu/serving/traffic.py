"""Open-loop traffic generation for the fleet fabric (ISSUE 18).

Serving benchmarks that submit the next request only after the last one
finished (closed-loop) can never observe queueing collapse — the
arrival process slows down exactly when the system does. This module
generates OPEN-LOOP Poisson arrivals: exponential inter-arrival gaps at
a configured rate, independent of completion, with mixed prompt/output
lengths and an optional burst window where the rate multiplies. That is
the traffic shape under which the autoscaler's burn/queue signals mean
something.

``run_episode`` paces the trace against the wall clock through a
:class:`~.fleet.FleetRouter`: arrivals whose time has come are
submitted, the router steps, and an optional fault injection kills the
busiest replica mid-episode (the re-prefill path under live load). The
episode ends when every future resolved, and dumps the whole fleet
black box for ``scripts/slo_report.py --fleet`` replay.

Everything is seeded — the same config replays the same arrivals.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from .fleet import FleetRouter


@dataclass(frozen=True)
class TrafficConfig:
    """One episode's arrival process."""
    rate_rps: float = 20.0          # base arrival rate
    duration_s: float = 2.0         # arrivals stop after this
    prompt_lens: Tuple[int, ...] = (4, 8, 16)
    max_new_tokens: Tuple[int, ...] = (4, 8)
    vocab: int = 256                # prompt ids drawn from [1, vocab)
    burst_start_s: Optional[float] = None
    burst_end_s: Optional[float] = None
    burst_mult: float = 4.0         # rate multiplier inside the burst
    sessions: int = 0               # >0: requests cycle this many
    #                                 session ids (affinity traffic)
    temperature: float = 0.0
    seed: int = 0

    def rate_at(self, t: float) -> float:
        if self.burst_start_s is not None and self.burst_end_s is not None \
                and self.burst_start_s <= t < self.burst_end_s:
            return self.rate_rps * self.burst_mult
        return self.rate_rps


@dataclass(frozen=True)
class Arrival:
    t: float                        # seconds from episode start
    prompt: np.ndarray
    max_new_tokens: int
    session_id: Optional[str]


def poisson_arrivals(cfg: TrafficConfig) -> List[Arrival]:
    """The seeded open-loop trace: piecewise-homogeneous Poisson (the
    gap after time t is drawn at rate ``cfg.rate_at(t)``)."""
    rng = np.random.default_rng(cfg.seed)
    out: List[Arrival] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / cfg.rate_at(t)))
        if t >= cfg.duration_s:
            return out
        plen = int(rng.choice(np.asarray(cfg.prompt_lens)))
        prompt = rng.integers(1, cfg.vocab, size=plen,
                              dtype=np.int64).astype(np.int32)
        mnt = int(rng.choice(np.asarray(cfg.max_new_tokens)))
        sid = f"s{int(rng.integers(cfg.sessions))}" if cfg.sessions \
            else None
        out.append(Arrival(t=t, prompt=prompt, max_new_tokens=mnt,
                           session_id=sid))


@dataclass
class EpisodeReport:
    submitted: int
    completed: int
    failed: int
    wall_s: float
    killed_rid: Optional[int]
    dump_path: Optional[str]
    fleet: dict
    futures: list = field(default_factory=list, repr=False)


def _busiest_live_rid(router: FleetRouter) -> Optional[int]:
    """The live replica holding the most outstanding leases (ties to
    the lowest rid); None when killing it would leave no survivor."""
    with router._lock:
        live = router._live_locked()
        if len(live) < 2:
            return None
        held = {rep.rid: 0 for rep in live}
        for rec in router.outstanding.values():
            if rec.rid in held:
                held[rec.rid] += 1
        return max(sorted(held), key=lambda rid: held[rid])


def run_episode(router: FleetRouter, cfg: TrafficConfig, *,
                kill_at_s: Optional[float] = None,
                dump_path=None, max_wall_s: float = 120.0,
                eos_id: Optional[int] = None) -> EpisodeReport:
    """Pace ``cfg``'s trace through ``router`` against the wall clock.

    ``kill_at_s`` injects one replica death at that episode time (the
    busiest live replica, skipped if no survivor would remain);
    ``dump_path`` appends the fleet black box there at episode end.
    Raises if the episode exceeds ``max_wall_s`` — no hidden hang."""
    arrivals = poisson_arrivals(cfg)
    futures = []
    killed: Optional[int] = None
    t0 = time.perf_counter()
    i = 0
    while True:
        now = time.perf_counter() - t0
        if now > max_wall_s:
            raise RuntimeError(
                f"episode exceeded max_wall_s={max_wall_s} "
                f"({len(futures)} submitted, "
                f"{len(router.outstanding)} outstanding)")
        if kill_at_s is not None and killed is None and now >= kill_at_s:
            rid = _busiest_live_rid(router)
            if rid is not None:
                router.kill_replica(rid)
                killed = rid
        while i < len(arrivals) and arrivals[i].t <= now:
            a = arrivals[i]
            i += 1
            futures.append(router.submit(
                a.prompt, a.max_new_tokens,
                temperature=cfg.temperature, eos_id=eos_id,
                session_id=a.session_id))
        worked = router.step()
        if i >= len(arrivals) and not router.outstanding:
            break
        if not worked and i < len(arrivals):
            # idle with the next arrival still in the future: nap until
            # it (bounded — the router stays responsive to the clock)
            time.sleep(max(0.0, min(arrivals[i].t - now, 0.005)))
    wall = time.perf_counter() - t0
    completed = sum(1 for f in futures
                    if f.done() and f.exception() is None)
    failed = sum(1 for f in futures
                 if f.done() and f.exception() is not None)
    dump = router.dump(dump_path) if dump_path is not None else None
    return EpisodeReport(submitted=len(futures), completed=completed,
                         failed=failed, wall_s=round(wall, 3),
                         killed_rid=killed, dump_path=dump,
                         fleet=router.fleet_report(), futures=futures)
