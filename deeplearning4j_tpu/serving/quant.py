"""Quantization plane (ISSUE 19): int8 KV-cache storage and int8 weight
storage behind the fidelity gate.

Decode is memory-bound — the floor plane has said so since PR 7 — so
the decode path gets faster only by moving fewer bytes per token. This
module shrinks the two byte streams the decode sweep actually reads:

- **int8 KV pages** — rows quantize at page append (symmetric,
  per-row-per-head ``amax/127`` scales) and dequantize inside the
  gather/attention closure. The scale arrays share the pool's page
  axis, so every page-table operation the serving stack already has —
  CoW splits, prefix sharing, release, spec-decode trim, fleet
  re-prefill — carries scales and rows as one unit with zero new
  bookkeeping. Per-row scales (not per-page) are deliberate: pages
  fill incrementally, and a page-wide running amax would requantize
  resident rows on every growth, compounding error.
- **int8 weights, bf16 compute** — the block-stack matvec weights
  (wqkv/wo/w_in/w_out) quantize ONCE per engine with per-output-channel
  scales and dequantize on the fly inside ``_blocks_with_cache``'s
  ``_wload``; embeddings, norms and the head stay full precision, and
  the prefill trunk never sees quantized weights (prompt fidelity is
  not where the bytes are).

Neither mode is dispatched on faith. Promotion is per-mode and
per-shape-bucket through the unified autotune harness
(``kernels/autotune.py``), exactly the ISSUE 17 paged-kernel contract:
``race_*`` runs the quantized arm against the bf16 arm on identical
probe content, gates on the FidelityProbe's ``kl_max`` under
:data:`PROMOTION_MAX_KL` (the ``fidelity_report.py --max-kl`` bound),
requires a measured speed-or-bytes win, persists the verdict as a
sha-stamped ``quant_kv:*`` / ``quant_w:*`` cost record, and bumps
``dl4j_autotune_promotions_total{kernel,verdict}``. Losers fall back
silently — the caller gets bf16 and never knows a race happened.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..kernels import autotune
from ..kernels.paged_attention import PROMOTION_MAX_KL
from . import kvcache

#: symmetric int8 range: scales are amax/127, values clip to ±127
QMAX = 127.0

#: env knobs for the two dispatch modes when the engine doesn't pin
#: one: auto (race on TPU, bf16 elsewhere) | race | on | off
_KV_MODE_ENV = "DL4J_QUANT_KV"
_W_MODE_ENV = "DL4J_QUANT_W"

_OFF = ("off", "0", "bf16", "none")
_ON = ("on", "1", "int8")


# --------------------------------------------------------- primitives --

def quantize_rows(rows):
    """Symmetric int8 quantization of k/v rows ``(..., H, Dh)`` in one
    shot: per-row-per-head scale ``amax(|row|)/127`` (f32), values
    rounded and clipped to ±127. Returns ``(int8 rows (..., H, Dh),
    f32 scales (..., H))`` — the shapes the quantized pool's page
    scatter writes side by side."""
    r = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(r), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / QMAX
    q = jnp.clip(jnp.round(r / scale[..., None]), -QMAX, QMAX)
    return q.astype(jnp.int8), scale


def dequantize_rows(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_rows` (the gather-side dequant)."""
    return (q.astype(jnp.float32)
            * scale[..., None].astype(jnp.float32)).astype(dtype)


#: the block-stack matvec weights the int8 weight path stores quantized
_W_NAMES = ("wqkv", "wo", "w_in", "w_out")


def quantize_block_weights(blocks) -> Dict:
    """Quantize the stacked block matvec weights ``(L, in, out)`` to
    int8 with per-output-channel scales ``(L, 1, out)`` stored under
    ``name + "_scale"`` — the layout ``engine._wload`` dequantizes on
    the fly (the lax.scan layer slice broadcasts ``(1, out)`` against
    ``(in, out)``). Norm weights stay full precision."""
    out = dict(blocks)
    for name in _W_NAMES:
        w = jnp.asarray(blocks[name], jnp.float32)
        amax = jnp.max(jnp.abs(w), axis=1, keepdims=True)   # (L, 1, out)
        scale = jnp.maximum(amax, 1e-12) / QMAX
        out[name] = jnp.clip(jnp.round(w / scale), -QMAX, QMAX) \
            .astype(jnp.int8)
        out[name + "_scale"] = scale.astype(jnp.float32)
    return out


def quantized_params(params) -> Dict:
    """Params with ONLY the block stack replaced by its int8 form —
    embed/pos_embed/ln_f/head are shared arrays, not copies, so the
    int8 engine holds one extra copy of the (shrunken) blocks and
    nothing else."""
    return dict(params, blocks=quantize_block_weights(params["blocks"]))


def quant_sha() -> str:
    """Source fingerprint stamped on every ``quant_kv:*``/``quant_w:*``
    cost record — editing the quantization math auto-invalidates stale
    promotion verdicts on next lookup (kernels/autotune.py)."""
    return autotune.source_sha(quantize_rows, quantize_block_weights)


# ---------------------------------------------------------- promotion --

def kv_bucket_key(cfg, n_slots: int, n_pages: int, page_len: int,
                  backend: Optional[str] = None) -> str:
    """Shape-bucket cost-record key for one paged-pool geometry."""
    if backend is None:
        backend = jax.default_backend()
    return (f"quant_kv:L{cfg.n_layers}H{cfg.n_heads}D{cfg.head_dim}"
            f":PL{int(page_len)}:NP{int(n_pages)}:S{int(n_slots)}"
            f":{jnp.dtype(cfg.dtype).name}:{backend}")


def w_bucket_key(cfg, backend: Optional[str] = None) -> str:
    """Shape-bucket cost-record key for one block-stack geometry."""
    if backend is None:
        backend = jax.default_backend()
    return (f"quant_w:L{cfg.n_layers}H{cfg.n_heads}D{cfg.head_dim}"
            f"F{cfg.d_ff}:{jnp.dtype(cfg.dtype).name}:{backend}")


def _fid_compact(rep: Dict) -> Dict:
    keep = ("max_abs_err", "mean_abs_err", "kl_mean", "kl_max",
            "topk_agreement", "greedy_match_frac", "greedy_prefix_len",
            "positions")
    return {k: rep[k] for k in keep if k in rep}


def _probe_paged(cfg, n_slots: int, n_pages: int, page_len: int,
                 max_len: int, quantized: bool, rng):
    """A probe pool of the live geometry: random k/v content, every
    slot mapped to ~3/4 of its table width, cursors mid-page — the
    paged-kernel race's probe recipe (its signatures ARE the live
    sweep's). The quantized probe holds the SAME content, pushed
    through :func:`quantize_rows`, so the fidelity diff measures
    quantization error and nothing else. Returns (cache, tokens)."""
    base = kvcache.init_paged_cache(cfg, n_slots, n_pages, page_len,
                                    max_len)
    kshape = base["k"].shape
    per_slot = base["pages"].shape[1]
    table = np.full((n_slots, per_slot), n_pages, np.int32)
    nxt = 0
    pos = np.zeros((n_slots,), np.int32)
    for s in range(n_slots):
        want = max(1, (3 * per_slot) // 4)
        got = min(want, n_pages - nxt)
        if got < 1:
            continue
        table[s, :got] = np.arange(nxt, nxt + got)
        nxt += got
        pos[s] = (got - 1) * page_len + page_len // 2
    k = rng.standard_normal(kshape).astype(np.float32)
    v = rng.standard_normal(kshape).astype(np.float32)
    cache = {"pos": jnp.asarray(pos), "pages": jnp.asarray(table)}
    if quantized:
        qk, sk = quantize_rows(jnp.asarray(k))
        qv, sv = quantize_rows(jnp.asarray(v))
        cache.update(k=qk, v=qv, k_scale=sk, v_scale=sv)
    else:
        cache.update(k=jnp.asarray(k, base["k"].dtype),
                     v=jnp.asarray(v, base["v"].dtype))
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (n_slots,)),
                       jnp.int32)
    return cache, toks


def _promote(key: str, kernel: str, arms: Dict[str, float],
             cand: str, ref: str, fid: Dict, fidelity_ok: bool,
             max_kl: float, extra: Optional[Dict] = None) -> Dict:
    """Shared verdict/record/counter tail of every race here: pick the
    candidate only when fidelity holds AND it measured faster, persist
    the sha-stamped record, bump the promotions counter."""
    from ..obs import get_registry

    if fidelity_ok:
        chosen = cand if arms[cand] < arms[ref] else ref
        verdict = "promoted" if chosen == cand else "fallback_slower"
    else:
        chosen, verdict = ref, "fallback_fidelity"
    meta = {
        "verdict": verdict,
        f"{ref}_s": arms.get(ref),
        f"{cand}_s": arms.get(cand),
        "speedup": (round(arms[ref] / arms[cand], 3)
                    if arms.get(cand) else None),
        "max_kl": max_kl,
        "fidelity": _fid_compact(fid),
        "backend": jax.default_backend(),
    }
    if extra:
        meta.update(extra)
    autotune.put(key, (chosen,), meta=meta, sha=quant_sha())
    get_registry().counter(
        "dl4j_autotune_promotions_total",
        "Fidelity-gated kernel-vs-XLA promotion races, by verdict",
        labelnames=("kernel", "verdict")).inc(
            kernel=kernel, verdict=verdict)
    return dict(meta, choice=chosen, key=key)


def race_kv(engine, n_slots: int, n_pages: int,
            page_len: int = kvcache.DEFAULT_PAGE_LEN, *,
            max_kl: float = PROMOTION_MAX_KL) -> Dict:
    """Race the int8 pool against the bf16 pool on identical probe
    content at one geometry; gate on ``kl_max``; persist the verdict.

    Verdicts: ``promoted`` (fidelity holds, int8 decode measured
    faster), ``fallback_slower``, ``fallback_fidelity`` — the latter
    two leave the bf16 pool dispatched silently."""
    from ..obs.fidelity import FidelityProbe

    cfg = engine.cfg
    key = kv_bucket_key(cfg, n_slots, n_pages, page_len)
    rng = np.random.default_rng(0)

    ref_probe, toks = _probe_paged(cfg, n_slots, n_pages, page_len,
                                   engine.max_len, False, rng)
    rng = np.random.default_rng(0)          # same draw -> same content
    cand_probe, _ = _probe_paged(cfg, n_slots, n_pages, page_len,
                                 engine.max_len, True, rng)
    params = engine._decode_params()
    ref_logits, _ = engine._decode_paged(params, ref_probe, toks)
    cand_logits, _ = engine._decode_paged(params, cand_probe, toks)
    fid = FidelityProbe("quant_kv_vs_bf16").compare(
        np.asarray(ref_logits, np.float32),
        np.asarray(cand_logits, np.float32))
    fidelity_ok = fid["kl_max"] <= max_kl

    arms: Dict[str, float] = {}
    for name, quantized in (("bf16", False), ("int8", True)):
        state: Dict = {}
        rng = np.random.default_rng(0)
        state["cache"], state["toks"] = _probe_paged(
            cfg, n_slots, n_pages, page_len, engine.max_len, quantized,
            rng)

        def run():
            logits, state["cache"] = engine._decode_paged(
                params, state["cache"], state["toks"])
            return logits

        arms[name] = autotune._time_once(run)
    bpt = {name: kvcache.token_nbytes(
        kvcache.init_paged_cache(cfg, 1, 1, page_len, engine.max_len,
                                 quantized=(name == "int8")))
        for name in ("bf16", "int8")}
    return _promote(key, "quant_kv", arms, "int8", "bf16", fid,
                    fidelity_ok, max_kl,
                    extra={"bytes_per_token": bpt})


def race_weights(engine, *, max_kl: float = PROMOTION_MAX_KL) -> Dict:
    """Race int8-weight decode against bf16-weight decode on one dense
    probe cache; gate on ``kl_max``; persist the verdict (same
    vocabulary as :func:`race_kv`)."""
    from ..obs.fidelity import FidelityProbe

    cfg = engine.cfg
    key = w_bucket_key(cfg)
    qparams = quantized_params(engine.params)
    rng = np.random.default_rng(0)
    probe_len = min(engine.max_len, 256)

    def probe():
        r = np.random.default_rng(0)
        shape = (cfg.n_layers, 2, probe_len, cfg.n_heads, cfg.head_dim)
        cache = {"k": jnp.asarray(r.standard_normal(shape), cfg.dtype),
                 "v": jnp.asarray(r.standard_normal(shape), cfg.dtype),
                 "pos": jnp.full((2,), probe_len // 2, jnp.int32)}
        toks = jnp.asarray(r.integers(0, cfg.vocab_size, (2,)), jnp.int32)
        return cache, toks

    del rng
    cache_a, toks = probe()
    cache_b, _ = probe()
    ref_logits, _ = engine._decode(engine.params, cache_a, toks)
    cand_logits, _ = engine._decode(qparams, cache_b, toks)
    fid = FidelityProbe("quant_w_vs_bf16").compare(
        np.asarray(ref_logits, np.float32),
        np.asarray(cand_logits, np.float32))
    fidelity_ok = fid["kl_max"] <= max_kl

    arms: Dict[str, float] = {}
    for name, p in (("bf16", engine.params), ("int8", qparams)):
        state: Dict = {}
        state["cache"], state["toks"] = probe()

        def run():
            logits, state["cache"] = engine._decode(p, state["cache"],
                                                    state["toks"])
            return logits

        arms[name] = autotune._time_once(run)
    return _promote(key, "quant_w", arms, "int8", "bf16", fid,
                    fidelity_ok, max_kl)


# ----------------------------------------------------------- dispatch --

def _resolve_mode(pinned: Optional[str], env: str) -> str:
    mode = pinned if pinned is not None else os.environ.get(env, "auto")
    return str(mode).lower()


def decide_kv(engine, n_slots: int, n_pages: int,
              page_len: int = kvcache.DEFAULT_PAGE_LEN,
              mode: Optional[str] = None) -> str:
    """``"int8"`` or ``"bf16"`` for one pool geometry. Resolution:
    ``mode`` (or the engine's pinned ``quant_kv_mode``, or
    ``$DL4J_QUANT_KV``): ``off`` → bf16, ``on`` → int8 (no race);
    ``auto`` off-TPU → bf16; ``race``/auto-on-TPU → the cached
    sha-stamped verdict, else :func:`race_kv`. Every resolution bumps
    ``dl4j_quant_pool_total{kernel,mode}`` — the allocation census the
    quant bench row and /debug pages read."""
    if mode is None:
        mode = _resolve_mode(getattr(engine, "quant_kv_mode", None),
                             _KV_MODE_ENV)
    mode = str(mode).lower()
    if mode in _OFF:
        choice = "bf16"
    elif mode in _ON:
        choice = "int8"
    elif mode == "auto" and jax.default_backend() != "tpu":
        choice = "bf16"
    else:
        rec = autotune.lookup(
            kv_bucket_key(engine.cfg, n_slots, n_pages, page_len),
            sha=quant_sha())
        if rec is not None and rec["choice"]:
            choice = str(rec["choice"][0])
        else:
            choice = str(race_kv(engine, n_slots, n_pages,
                                 page_len)["choice"])
    from ..obs import get_registry
    get_registry().counter(
        "dl4j_quant_pool_total",
        "KV pools allocated, by resolved storage mode",
        labelnames=("kernel", "mode")).inc(kernel="quant_kv", mode=choice)
    return choice


def decide_weights(engine, mode: Optional[str] = None) -> str:
    """``"int8"`` or ``"bf16"`` for the engine's decode weights — same
    resolution ladder as :func:`decide_kv` over ``quant_weights_mode``
    / ``$DL4J_QUANT_W``, with the verdict cached per block-stack shape
    bucket. Bumps ``dl4j_quant_weights_total{kernel,mode}``."""
    if mode is None:
        mode = _resolve_mode(getattr(engine, "quant_weights_mode", None),
                             _W_MODE_ENV)
    mode = str(mode).lower()
    if mode in _OFF:
        choice = "bf16"
    elif mode in _ON:
        choice = "int8"
    elif mode == "auto" and jax.default_backend() != "tpu":
        choice = "bf16"
    else:
        rec = autotune.lookup(w_bucket_key(engine.cfg), sha=quant_sha())
        if rec is not None and rec["choice"]:
            choice = str(rec["choice"][0])
        else:
            choice = str(race_weights(engine)["choice"])
    from ..obs import get_registry
    get_registry().counter(
        "dl4j_quant_weights_total",
        "Engine decode-weight resolutions, by storage mode",
        labelnames=("kernel", "mode")).inc(kernel="quant_w", mode=choice)
    return choice
