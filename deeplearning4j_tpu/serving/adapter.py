"""Adapter: serve a pure-functional forward through ``ParallelInference``.

The zoo's functional models (the Transformer-LM, BERT) are params+fn
pairs, not ``MultiLayerNetwork``/``ComputationGraph`` objects — so the
dynamic-batching / dp-sharded serving machinery in ``parallel.wrapper``
couldn't touch them. This shim gives a functional forward the four
attributes ``ParallelInference`` actually uses (``params``, ``states``,
``conf.nodes``, ``_forward``) and nothing else; params land under one
``"model"`` key and resolve to replicated sharding (no layer op to
declare tp pspecs).

    bert = FunctionalInferenceModel(
        params, lambda p, ids: tfm.bert_forward(p, cfg, ids)[0])
    pi = ParallelInference(bert, max_batch=8, max_wait_ms=5.0)
    logits = pi.output(ids)          # or pi.submit(ids) for batching
"""

from __future__ import annotations


class _EmptyConf:
    """Just enough of a net conf for ``network_param_shardings``."""
    nodes: dict = {}


class FunctionalInferenceModel:
    """Wrap ``forward(params, x) -> y`` for ``ParallelInference``."""

    def __init__(self, params, forward):
        self.params = {"model": params}
        self.states = {}
        self.conf = _EmptyConf()
        self._fwd = forward
        self.initialized = True

    def _forward(self, params, states, x, train=False, rng=None):
        return self._fwd(params["model"], x), states
