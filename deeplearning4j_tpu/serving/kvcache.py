"""Per-layer KV cache for the transformer serving plane — dense slots
and block-paged pages (ISSUE 14).

**Dense layout** (the original μ-cuDNN static slotting): one cache
serves one fixed pool of decode SLOTS, each preallocated to ``max_len``
rows. Layout mirrors the model's stacked-block parameterization so a
``lax.scan`` over layers can consume and re-emit the cache
layer-by-layer:

    {"k":   (L, n_slots, max_len, H, Dh)   compute dtype,
     "v":   (L, n_slots, max_len, H, Dh)   compute dtype,
     "pos": (n_slots,)                     int32}

``pos[s]`` is the number of tokens already resident in slot ``s`` —
equivalently the index the NEXT token's k/v will be written at, and the
inclusive upper bound of the attention mask for that slot. The cache is
a plain pytree: the engine's jitted ``decode_step`` donates it, so the
HBM buffers are updated in place across the whole decode loop and the
allocation cost is paid once per pool, not per token.

**Paged layout** (ISSUE 14 — the fix for the measured 96% waste of
dense slotting under mixed-length traffic): the pool is a fixed set of
fixed-size PAGES shared by every slot, plus a per-slot page table of
device gather indices:

    {"k":     (L, n_pages, page_len, H, Dh)      compute dtype,
     "v":     (L, n_pages, page_len, H, Dh)      compute dtype,
     "pos":   (n_slots,)                          int32,
     "pages": (n_slots, pages_per_slot)           int32}

``pages[s, j]`` is the pool page holding slot ``s``'s tokens
``[j*page_len, (j+1)*page_len)``; unmapped entries hold the sentinel
``n_pages`` (one past the pool) so a stray gather CLAMPS to masked
garbage and a stray scatter DROPS — a freed lane can never corrupt a
neighbour's live page. The page table is fixed-width
(``pages_per_slot = ceil(max_len / page_len)``), so the attention
gather shape is static and page-table GROWTH never retraces: mapping a
new page is a data change, not a shape change.

A short request holds ``ceil(len/page_len)`` pages instead of
``max_len`` rows, so the byte budget buys concurrency proportional to
*actual* token residency. The host side of the mapping lives in
:class:`PageTable` (free list + numpy mirror of ``pages``); the device
side rides the cache pytree through the same donated entry points as
the dense cache.

**Copy-on-write prefix sharing** (ISSUE 16): pages are REF-COUNTED, so
one pool page may back the same token prefix in many slots at once —
the gather attention reads arbitrary page sets, so sharing needs zero
jitted-code changes. :class:`PrefixCache` keeps a radix-style index
over resident pages (each page-aligned token block hashed chained on
its predecessor's hash) plus per-session retention entries; admission
matches an incoming prompt against it, maps the shared prefix via
:meth:`PageTable.map_shared`, and chunk-prefills only the unmatched
tail. A slot about to scatter into a page with other holders first
splits it (:meth:`PageTable.cow` + one device page copy). Pages whose
only holders are cache entries ("cached" state) are LRU-evicted under
page pressure, before the scheduler's preemption path. The page
lifecycle: free → mapped → shared → cow-split → cached → evicted.

``DEFAULT_PAGE_LEN = 16`` follows the vLLM block-size precedent and the
``serving_page_len:*`` autotune cost records (``serving/tune.py``
re-measures it per shape/dtype/backend into the persistent autotune
cache).
"""

from __future__ import annotations

import hashlib

from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

# page size (tokens) — vLLM-style small blocks keep per-request
# over-allocation under one page; re-derived per shape/backend by
# serving.tune.sweep_serving_knobs into the autotune disk cache
DEFAULT_PAGE_LEN = 16
# prompt tokens one chunked-prefill dispatch processes (ISSUE 14):
# small enough that one chunk costs about one decode sweep (the ITL
# interleave contract), large enough to amortize dispatch — re-measured
# per shape/backend by the serving_prefill_chunk autotune records
DEFAULT_PREFILL_CHUNK = 128


def init_cache(cfg, n_slots: int, max_len=None, dtype=None):
    """Allocate an empty cache for ``n_slots`` concurrent sequences.

    ``max_len`` defaults to ``cfg.max_seq`` and may not exceed it: the
    learned position table has ``cfg.max_seq`` rows, so a longer cache
    would hold positions the model cannot embed.
    """
    max_len = int(cfg.max_seq if max_len is None else max_len)
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds cfg.max_seq={cfg.max_seq}: the "
            "position-embedding table has no rows past max_seq")
    if max_len < 1 or n_slots < 1:
        raise ValueError(f"need max_len >= 1 and n_slots >= 1, got "
                         f"max_len={max_len}, n_slots={n_slots}")
    dt = cfg.dtype if dtype is None else dtype
    shape = (cfg.n_layers, int(n_slots), max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((int(n_slots),), jnp.int32)}


def is_paged(cache) -> bool:
    """True for the block-paged layout (ISSUE 14)."""
    return "pages" in cache


def is_quantized(cache) -> bool:
    """True when the pool stores int8 rows + per-row-per-head scales
    (ISSUE 19). The scale arrays share the page axis, so every page
    operation (CoW copy, prefix sharing, release, trim) moves scales
    and rows as one unit."""
    return "k_scale" in cache


def cache_len(cache) -> int:
    """Static per-slot capacity (tokens). For a paged cache this is the
    page-table ceiling ``pages_per_slot * page_len`` — what one slot
    could address if it mapped every entry, NOT what it has mapped."""
    if is_paged(cache):
        return cache["pages"].shape[1] * cache["k"].shape[2]
    return cache["k"].shape[2]


def cache_slots(cache) -> int:
    """Number of decode slots the cache was allocated for."""
    return cache["pos"].shape[0]


def page_len(cache) -> int:
    """Tokens per page (paged layout only)."""
    return cache["k"].shape[2]


def n_pages(cache) -> int:
    """Pool pages (paged layout only)."""
    return cache["k"].shape[1]


def pages_per_slot(cache) -> int:
    """Page-table width (paged layout only)."""
    return cache["pages"].shape[1]


def cache_nbytes(cache) -> int:
    """Total device bytes held by the cache (capacity planning: at the
    flagship 120M config a T=1024 slot is L8·T1024·H8·Dh64 · 2 tensors
    · 2 bytes = 16 MiB). For a paged cache this is the fixed POOL
    footprint — what the device actually reserves, regardless of how
    many pages are mapped."""
    return int(sum(a.size * a.dtype.itemsize for a in cache.values()))


def token_nbytes(cache) -> int:
    """Bytes ONE resident token occupies in one slot: k + v rows across
    every layer (shape positions are shared by both layouts). Resident
    tokens × token_nbytes vs the allocated bytes is the KV residency
    accounting (ISSUE 12/14): dense waste is the ``max_len - resident``
    tail a short request preallocates; paged waste is only the unfilled
    remainder of the LAST mapped page. A quantized pool adds the two
    per-row-per-head scale entries (ISSUE 19) — at the flagship shape
    that is 8-bit rows + 4-byte scales ≈ 53% of the bf16 row."""
    layers, _, _, heads, head_dim = cache["k"].shape
    n = 2 * layers * heads * head_dim * cache["k"].dtype.itemsize
    if is_quantized(cache):
        n += 2 * layers * heads * cache["k_scale"].dtype.itemsize
    return int(n)


def page_nbytes(cache) -> int:
    """Bytes one PAGE holds across every layer (paged layout)."""
    return page_len(cache) * token_nbytes(cache)


def init_paged_cache(cfg, n_slots: int, n_pages: int,
                     page_len: int = DEFAULT_PAGE_LEN, max_len=None,
                     dtype=None, quantized: bool = False):
    """Allocate an empty block-paged pool: ``n_pages`` shared pages of
    ``page_len`` tokens each, a per-slot cursor, and a per-slot page
    table sized ``ceil(max_len / page_len)`` entries (initially all the
    ``n_pages`` sentinel = unmapped). ``max_len`` bounds what ONE slot
    may address (defaults to ``cfg.max_seq``, same rule as the dense
    cache); the pool itself may hold far fewer than
    ``n_slots * max_len`` tokens — that is the point."""
    max_len = int(cfg.max_seq if max_len is None else max_len)
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds cfg.max_seq={cfg.max_seq}: the "
            "position-embedding table has no rows past max_seq")
    if page_len < 1 or n_pages < 1 or n_slots < 1 or max_len < 1:
        raise ValueError(
            f"need page_len/n_pages/n_slots/max_len >= 1, got "
            f"page_len={page_len}, n_pages={n_pages}, n_slots={n_slots}, "
            f"max_len={max_len}")
    per_slot = -(-max_len // int(page_len))          # ceil
    dt = cfg.dtype if dtype is None else dtype
    shape = (cfg.n_layers, int(n_pages), int(page_len), cfg.n_heads,
             cfg.head_dim)
    if quantized:
        # int8 rows + f32 per-row-per-head scales riding the same page
        # axis (ISSUE 19): the gather/scatter/CoW paths address scales
        # with the page table entries they already compute, so sharing
        # and splits need zero extra bookkeeping
        sshape = shape[:-1]
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.float32),
                "v_scale": jnp.zeros(sshape, jnp.float32),
                "pos": jnp.zeros((int(n_slots),), jnp.int32),
                "pages": jnp.full((int(n_slots), per_slot), int(n_pages),
                                  jnp.int32)}
    return {"k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((int(n_slots),), jnp.int32),
            "pages": jnp.full((int(n_slots), per_slot), int(n_pages),
                              jnp.int32)}


class PageTable:
    """Host side of the paged mapping: the free list, per-page
    refcounts, and the numpy mirror of the device ``pages`` table. The
    scheduler maps pages before a dispatch needs them and releases its
    holds when a request finishes / is preempted / is cancelled;
    :meth:`sync` hands the mirror to the device only when it changed
    (a (n_slots, P) int32 transfer — never a retrace, the shape is
    fixed).

    Pages are ref-counted (ISSUE 16): a slot mapping a page holds one
    ref, a :class:`PrefixCache` entry or session retaining it holds
    another, and the page returns to the free list only at refcount
    zero. Invariants (``check()`` asserts them; the fuzz tests hammer
    them): a page is FREE xor ref-counted (the ISSUE 14
    free-xor-mapped-once invariant generalized), slot mappings never
    exceed a page's refcount, and — given the cache's hold census —
    slot maps + cache holds equal the refcount exactly.
    """

    def __init__(self, n_slots: int, n_pages: int, page_len: int,
                 pages_per_slot: int):
        self.n_slots = int(n_slots)
        self.n_pages = int(n_pages)
        self.page_len = int(page_len)
        self.pages_per_slot = int(pages_per_slot)
        # pop() from the end → pages hand out in increasing id order
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self.table = np.full((self.n_slots, self.pages_per_slot),
                             self.n_pages, np.int32)
        self.mapped = np.zeros((self.n_slots,), np.int32)
        # holders per pool page (slots + cache entries), and the token
        # fill census behind shared-counted-once residency accounting
        self.refcount = np.zeros((self.n_pages,), np.int32)
        self.fill = np.zeros((self.n_pages,), np.int32)
        self._dirty = True                    # device mirror stale?

    @classmethod
    def for_cache(cls, cache) -> "PageTable":
        return cls(cache_slots(cache), n_pages(cache), page_len(cache),
                   pages_per_slot(cache))

    # ------------------------------------------------------- geometry
    def pages_for(self, tokens: int) -> int:
        """Pages required to hold ``tokens`` rows."""
        return -(-max(0, int(tokens)) // self.page_len)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def mapped_pages(self) -> int:
        """Per-slot mapping count summed — a SHARED page counts once
        per slot mapping it (per-slot capacity math). Residency
        accounting wants :attr:`used_pages` instead."""
        return int(self.mapped.sum())

    @property
    def used_pages(self) -> int:
        """Pool pages with at least one holder, each counted ONCE
        regardless of how many slots share it (ISSUE 16: the truthful
        allocated-bytes base)."""
        return self.n_pages - len(self._free)

    @property
    def shared_pages(self) -> int:
        """Pages with more than one holder (slot maps + cache holds)."""
        return int((self.refcount > 1).sum())

    @property
    def resident_tokens(self) -> int:
        """Tokens held across all resident pages, shared counted once
        (the :meth:`note_fill` census)."""
        return int(self.fill.sum())

    def slot_tokens_capacity(self, slot: int) -> int:
        """Tokens the slot's mapped pages can hold right now."""
        return int(self.mapped[slot]) * self.page_len

    def slot_pages(self, slot: int) -> List[int]:
        """The pool pages ``slot`` currently maps, in logical order —
        the list ``map_shared`` accepts, so a beam clone (ISSUE 20) or
        a prefix-cache insert reads a slot's mapping through one
        accessor instead of poking ``table``/``mapped`` directly."""
        return [int(p) for p in self.table[slot, :int(self.mapped[slot])]]

    # -------------------------------------------------------- mapping
    def can_map(self, slot: int, tokens: int) -> bool:
        need = self.pages_for(tokens) - int(self.mapped[slot])
        return need <= len(self._free)

    def _alloc(self) -> int:
        """Pop a fresh page off the free list: refcount 1, empty."""
        p = self._free.pop()
        self.refcount[p] = 1
        self.fill[p] = 0
        return p

    def map(self, slot: int, tokens: int) -> bool:
        """Grow ``slot``'s mapping to cover ``tokens`` rows with FRESH
        pages. All-or-nothing: returns False (mapping untouched) when
        the free list cannot cover the growth — the caller evicts
        cached prefix pages and/or preempts to make room."""
        want = self.pages_for(tokens)
        if want > self.pages_per_slot:
            raise ValueError(
                f"slot {slot} wants {want} pages "
                f"({tokens} tokens), page table holds "
                f"{self.pages_per_slot}")
        have = int(self.mapped[slot])
        need = want - have
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for j in range(have, want):
            self.table[slot, j] = self._alloc()
        self.mapped[slot] = want
        self._dirty = True
        return True

    def map_shared(self, slot: int, pages) -> None:
        """Map an admission's matched prefix (ISSUE 16): the already-
        resident ``pages`` become ``slot``'s logical pages
        ``0..len-1``, each gaining one ref. The slot must map nothing
        yet (admission-time only); growth past the prefix goes through
        :meth:`map` as usual."""
        if int(self.mapped[slot]):
            raise ValueError(f"slot {slot} already maps "
                             f"{int(self.mapped[slot])} pages")
        pages = [int(p) for p in pages]
        if len(pages) > self.pages_per_slot:
            raise ValueError(f"{len(pages)} shared pages exceed the "
                             f"{self.pages_per_slot}-entry page table")
        for p in pages:
            if not (0 <= p < self.n_pages) or self.refcount[p] < 1:
                raise ValueError(f"page {p} is not resident")
        for j, p in enumerate(pages):
            self.table[slot, j] = p
            self.refcount[p] += 1
        if pages:
            self.mapped[slot] = len(pages)
            self._dirty = True

    def incref(self, page: int):
        """Add a cache hold on a RESIDENT page (PrefixCache entries and
        session retention — the holds that keep a finished request's
        pages shareable)."""
        if not (0 <= int(page) < self.n_pages) or self.refcount[page] < 1:
            raise ValueError(f"page {page} is not resident")
        self.refcount[page] += 1

    def decref(self, page: int) -> int:
        """Drop one ref; a page reaching zero refs returns to the free
        list (free-XOR-refcounted). Returns 1 if the page freed, else
        0."""
        r = int(self.refcount[page]) - 1
        if r < 0:
            raise ValueError(f"page {page} is already free")
        self.refcount[page] = r
        if r == 0:
            self.fill[page] = 0
            self._free.append(int(page))
            return 1
        return 0

    def cow(self, slot: int, j: int):
        """Copy-on-write split of ``slot``'s logical page ``j`` — which
        must have other holders — before the slot scatters into it:
        remap the entry to a fresh page, drop one ref on the old one,
        and return ``(src, dst)`` pool ids for the caller's device page
        copy. Returns None when no free page exists (the caller evicts
        / preempts and retries)."""
        if not (0 <= j < int(self.mapped[slot])):
            raise ValueError(f"slot {slot} logical page {j} is unmapped")
        old = int(self.table[slot, j])
        if int(self.refcount[old]) <= 1:
            raise ValueError(
                f"page {old} is exclusively owned — no split needed")
        if not self._free:
            return None
        new = self._alloc()
        self.fill[new] = int(self.fill[old])
        self.table[slot, j] = new
        self.refcount[old] -= 1
        self._dirty = True
        return old, new

    def note_fill(self, slot: int, tokens: int):
        """Record the tokens ``slot``'s mapping holds into the per-page
        fill census (shared pages counted once via the per-page max):
        logical page ``j`` holds ``min(page_len, tokens - j*page_len)``
        rows, clamped to the mapped range."""
        t = max(0, int(tokens))
        for j in range(min(self.pages_for(t), int(self.mapped[slot]))):
            p = int(self.table[slot, j])
            f = min(self.page_len, t - j * self.page_len)
            if f > self.fill[p]:
                self.fill[p] = f

    def release(self, slot: int) -> int:
        """Drop ``slot``'s hold on every page it maps and reset its
        table row to the sentinel (so stale device writes from the
        freed lane DROP instead of landing in a re-issued page). Pages
        with remaining holders — shared prefixes, cached entries —
        stay resident; the rest return to the free list. Returns the
        number of mappings removed (NOT necessarily pages freed)."""
        have = int(self.mapped[slot])
        if have == 0:
            return 0
        for j in range(have - 1, -1, -1):     # LIFO: reuse hot pages
            self.decref(int(self.table[slot, j]))
        self.table[slot, :have] = self.n_pages
        self.mapped[slot] = 0
        self._dirty = True
        return have

    def trim(self, slot: int, tokens: int) -> int:
        """Shrink ``slot``'s mapping to cover exactly ``tokens`` rows —
        the speculative-decode rollback primitive (ISSUE 19). Pages past
        the kept range lose this slot's hold LIFO and their entries go
        back to the sentinel; shared pages survive through their other
        holders, exclusively-held ones return to the free list. Stale
        rows inside the LAST kept page (rejected draft tokens) are left
        in place — the attention mask never reads past ``pos`` and the
        next append overwrites them in order, the same contract release
        + remap already relies on. Returns mappings removed."""
        keep = self.pages_for(tokens)
        have = int(self.mapped[slot])
        if keep >= have:
            return 0
        for j in range(have - 1, keep - 1, -1):   # LIFO: reuse hot pages
            self.decref(int(self.table[slot, j]))
        self.table[slot, keep:have] = self.n_pages
        self.mapped[slot] = keep
        self._dirty = True
        return have - keep

    def reset(self):
        """Release everything (``_fail_all``). A PrefixCache layered on
        this table must ``forget()`` its holds in the same breath — the
        refcounts they backed are gone."""
        self._free = list(range(self.n_pages - 1, -1, -1))
        self.table[:] = self.n_pages
        self.mapped[:] = 0
        self.refcount[:] = 0
        self.fill[:] = 0
        self._dirty = True

    # --------------------------------------------------------- device
    def sync(self, cache):
        """Refresh the cache's device ``pages`` from the host mirror iff
        the mapping changed since the last sync. The engine's entry
        points DONATE the cache — including the pages buffer — so the
        live device table always travels inside the cache pytree; this
        uploads a fresh (n_slots, P) int32 array only on change (a tiny
        transfer, fixed shape — page growth is data, never a
        retrace)."""
        if self._dirty:
            cache = dict(cache, pages=jnp.asarray(self.table))
            self._dirty = False
        return cache

    # ------------------------------------------------------ invariant
    def check(self, external=None):
        """Assert the free-XOR-refcounted invariant; raises
        AssertionError with a diagnosis on violation (the fuzz
        harness's oracle).

        ``external`` maps page id -> hold count owed by layers above
        the table (PrefixCache entries + session retention). Every
        page's refcount must equal its slot mappings plus its external
        holds EXACTLY — a leaked or double-dropped ref is caught here,
        not as an eventual use-after-free. With no external holds this
        degenerates to the PR 14 free-xor-mapped-once check (shared
        mappings excepted, which only arise via ``map_shared``)."""
        ext = dict(external or {})
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate page in free list"
        for p in free:
            assert self.refcount[p] == 0, \
                f"page {p} free with refcount {int(self.refcount[p])}"
            assert self.fill[p] == 0, \
                f"page {p} free with fill {int(self.fill[p])}"
        slot_refs = np.zeros((self.n_pages,), np.int64)
        for s in range(self.n_slots):
            m = int(self.mapped[s])
            for j in range(self.pages_per_slot):
                p = int(self.table[s, j])
                if j < m:
                    assert 0 <= p < self.n_pages, \
                        f"slot {s} entry {j} unmapped below mapped count"
                    assert p not in free, \
                        f"page {p} mapped by slot {s} AND free"
                    slot_refs[p] += 1
                else:
                    assert p == self.n_pages, \
                        f"slot {s} entry {j} holds {p} past mapped count"
        for p in range(self.n_pages):
            assert int(slot_refs[p]) <= int(self.refcount[p]), (
                f"page {p} double-mapped: {int(slot_refs[p])} slot maps "
                f"exceed refcount {int(self.refcount[p])}")
            want = int(slot_refs[p]) + int(ext.get(p, 0))
            assert int(self.refcount[p]) == want, (
                f"page {p} refcount {int(self.refcount[p])} != "
                f"{int(slot_refs[p])} slot maps + {int(ext.get(p, 0))} "
                f"external holds")
        held = int((self.refcount > 0).sum())
        assert held + len(free) == self.n_pages, \
            f"lost pages: {self.n_pages - held - len(free)}"
        return True

    def report(self) -> dict:
        return {"n_pages": self.n_pages, "page_len": self.page_len,
                "pages_per_slot": self.pages_per_slot,
                "mapped_pages": self.mapped_pages,
                "used_pages": self.used_pages,
                "shared_pages": self.shared_pages,
                "free_pages": self.free_pages}


# --------------------------------------------------------------------------
# Prefix index over resident pages (ISSUE 16)
# --------------------------------------------------------------------------

#: hash-chain root: the parent digest of a prompt's first block
_ROOT = b"dl4j-prefix-root"


def _chain_hash(parent: bytes, block: np.ndarray) -> bytes:
    """Digest of one page-aligned token block chained on its
    predecessor's digest — radix-style, so a block's key encodes its
    entire prefix, and two prompts share an entry iff they share every
    token up to and including that block."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    h.update(np.ascontiguousarray(block, dtype=np.int32).tobytes())
    return h.digest()


class _PrefixEntry:
    """One FULL page of tokens resident in the pool, keyed by its
    chained block hash. Holds one table ref on its page for as long as
    it lives in the index."""

    __slots__ = ("page", "tokens", "parent", "children", "last_used")

    def __init__(self, page: int, tokens: np.ndarray,
                 parent: Optional[bytes], last_used: int):
        self.page = int(page)
        self.tokens = np.array(tokens, dtype=np.int32)  # defensive copy
        self.parent = parent          # predecessor's digest (chain walk)
        self.children = 0             # resident entries chained on us
        self.last_used = last_used


class _SessionEntry:
    """A finished request's written context retained verbatim so the
    session's next turn resumes append-only. Holds one table ref per
    page (the final partial page included — unlike the block index,
    which only keeps full pages)."""

    __slots__ = ("tokens", "pages", "last_used")

    def __init__(self, tokens: np.ndarray, pages: List[int],
                 last_used: int):
        self.tokens = np.array(tokens, dtype=np.int32)
        self.pages = [int(p) for p in pages]
        self.last_used = last_used


class PrefixCache:
    """Longest-prefix index + session retention over a :class:`PageTable`
    (ISSUE 16 tentpole part b/d).

    Pure host-side bookkeeping: entries key page-aligned token blocks by
    their chained hash and pin the backing pool page with one table ref
    (``incref``). Admission walks the chain over the incoming prompt's
    full blocks (:meth:`match`), maps whatever matched straight into
    the new slot's page table (``map_shared``) and prefills only the
    tail — the gather attention kernel reads arbitrary page sets, so
    sharing needs zero jitted-code changes. Sessions
    (:meth:`retain_session`) keep a finished request's ENTIRE written
    context, partial tail page included, so a follow-up turn resumes
    append-only (the boundary page copy-on-writes if appended into).

    Under page pressure the scheduler calls :meth:`evict`: zero-slot-ref
    cached pages drop LRU, leaves first (an inner chain entry never
    outlives its children — a dangling parent digest would match
    prompts whose earlier blocks are gone). Eviction runs BEFORE the
    preemption path — cold cache beats killing live requests.

    Collision paranoia: a digest match alone never shares a page;
    every hit re-verifies token equality against the entry's stored
    block before the page is mapped.
    """

    def __init__(self, table: PageTable):
        self.table = table
        self.entries: Dict[bytes, _PrefixEntry] = {}
        self.sessions: Dict[str, _SessionEntry] = {}
        self._holds: Dict[int, int] = {}   # page -> cache hold count
        self._clock = 0                    # LRU tick, monotonic
        self.hits = 0                      # admissions with >0 shared pages
        self.hit_tokens = 0                # prefill tokens skipped
        self.cow_copies = 0                # device page copies performed
        self.evictions = 0                 # pages freed by evict()

    # ------------------------------------------------------------ refs
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _hold(self, page: int):
        self.table.incref(page)
        self._holds[page] = self._holds.get(page, 0) + 1

    def _unhold(self, page: int) -> int:
        n = self._holds[page] - 1
        if n:
            self._holds[page] = n
        else:
            del self._holds[page]
        return self.table.decref(page)

    def holds(self) -> Dict[int, int]:
        """Page -> hold count owed by this cache — feed straight into
        ``PageTable.check(external=...)``."""
        return dict(self._holds)

    # ----------------------------------------------------------- match
    def match(self, tokens: np.ndarray) -> List[int]:
        """Longest resident prefix of ``tokens``: walk the chain over
        its full page-aligned blocks, verifying token equality at each
        hop, and return the matched pages in logical order. Bumps LRU
        on every entry touched."""
        tokens = np.asarray(tokens, dtype=np.int32)
        plen = self.table.page_len
        pages: List[int] = []
        parent = _ROOT
        now = self._tick()
        for j in range(len(tokens) // plen):
            block = tokens[j * plen:(j + 1) * plen]
            h = _chain_hash(parent, block)
            e = self.entries.get(h)
            if e is None or not np.array_equal(e.tokens, block):
                break
            e.last_used = now
            pages.append(e.page)
            parent = h
        return pages

    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Register ``tokens``' full page-aligned blocks, backed by the
        slot's ``pages`` (logical order), into the index. Idempotent:
        blocks already resident keep their FIRST page (the latecomer's
        copy stays slot-owned and frees on release); new blocks gain a
        cache hold on theirs. Returns the number of new entries."""
        tokens = np.asarray(tokens, dtype=np.int32)
        plen = self.table.page_len
        parent = _ROOT
        now = self._tick()
        added = 0
        prev: Optional[_PrefixEntry] = None
        for j in range(min(len(tokens) // plen, len(pages))):
            block = tokens[j * plen:(j + 1) * plen]
            h = _chain_hash(parent, block)
            e = self.entries.get(h)
            if e is None or not np.array_equal(e.tokens, block):
                if e is not None:       # true digest collision: keep old
                    break
                e = _PrefixEntry(pages[j], block,
                                 None if parent is _ROOT else parent, now)
                self._hold(e.page)
                self.entries[h] = e
                if prev is not None:
                    prev.children += 1
                added += 1
            else:
                e.last_used = now
            parent = h
            prev = e
        return added

    def note_hit(self, tokens_matched: int):
        """Account one admission that skipped ``tokens_matched`` prefill
        tokens via the index or a session."""
        self.hits += 1
        self.hit_tokens += int(tokens_matched)

    # -------------------------------------------------------- sessions
    def session_match(self, session_id: str,
                      tokens: np.ndarray) -> Optional[Tuple[int, List[int]]]:
        """If ``session_id``'s retained context is a strict prefix of
        ``tokens``, return ``(n_retained_tokens, pages)`` — the whole
        retained mapping, partial tail page included. Returns None on
        unknown session or divergence (caller falls back to the block
        index)."""
        s = self.sessions.get(session_id)
        if s is None:
            return None
        n = len(s.tokens)
        tokens = np.asarray(tokens, dtype=np.int32)
        if n > len(tokens) or not np.array_equal(s.tokens, tokens[:n]):
            return None
        s.last_used = self._tick()
        return n, list(s.pages)

    def retain_session(self, session_id: str, tokens: np.ndarray,
                       pages: List[int]):
        """Pin a finished request's written context under its session id
        (one hold per page). Replaces any previous retention for the
        id — each turn's retention supersedes the last."""
        self.drop_session(session_id)
        s = _SessionEntry(np.asarray(tokens, dtype=np.int32), pages,
                          self._tick())
        for p in s.pages:
            self._hold(p)
        self.sessions[session_id] = s

    def drop_session(self, session_id: str) -> bool:
        """Release a session's holds (explicit end-of-conversation, or
        supersession by the next turn)."""
        s = self.sessions.pop(session_id, None)
        if s is None:
            return False
        for p in reversed(s.pages):
            self._unhold(p)
        return True

    # -------------------------------------------------------- eviction
    def _slot_free(self, page: int) -> bool:
        """True when only this cache holds the page — no slot maps it,
        so dropping our hold(s) frees it."""
        return int(self.table.refcount[page]) == self._holds.get(page, 0)

    @property
    def cached_pages(self) -> int:
        """Pages resident ONLY because this cache holds them — the
        evictable reclaim headroom ``_ensure_pages`` taps before
        preempting."""
        return sum(1 for p in self._holds if self._slot_free(p))

    def _drop_entry(self, h: bytes) -> int:
        e = self.entries.pop(h)
        if e.parent is not None:
            parent = self.entries.get(e.parent)
            if parent is not None:
                parent.children -= 1
        return self._unhold(e.page)

    def evict(self, need: int, protect=frozenset()) -> int:
        """Free up to ``need`` pages by dropping cold cache state, LRU
        first: leaf index entries whose page no slot maps, then (and
        interleaved by age) whole sessions whose every page is
        slot-free. ``protect`` pins pages the caller just matched but
        has not yet mapped — eviction must never reclaim the prefix an
        admission is about to share. Returns pages actually freed."""
        freed = 0
        while freed < need:
            # candidate leaves: evictable index entries (no children —
            # inner nodes wait for their subtree) and whole sessions
            cand = []
            for h, e in self.entries.items():
                if (e.children == 0 and e.page not in protect
                        and self._slot_free(e.page)):
                    cand.append((e.last_used, 0, h))
            for sid, s in self.sessions.items():
                if s.pages and all(p not in protect and self._slot_free(p)
                                   for p in s.pages):
                    cand.append((s.last_used, 1, sid))
                elif not s.pages:
                    cand.append((s.last_used, 1, sid))
            if not cand:
                break
            cand.sort(key=lambda c: (c[0], c[1]))
            _, kind, key = cand[0]
            if kind == 0:
                freed += self._drop_entry(key)
            else:
                s = self.sessions.pop(key)
                for p in reversed(s.pages):
                    freed += self._unhold(p)
        self.evictions += freed
        return freed

    def release_page_holds(self, page: int) -> int:
        """Ownership-transfer escape hatch for CoW starvation: drop
        EVERY index entry and session touching ``page`` so the one slot
        still mapping it becomes the sole owner and can scatter in
        place — no copy, no free page needed. Entries chained below a
        dropped one are dropped too (their prefix is gone). Returns the
        holds removed from ``page``."""
        before = self._holds.get(page, 0)
        if not before:
            return 0
        # drop the subtree rooted at every entry on this page: child
        # entries' parent digests would dangle otherwise
        doomed = {h for h, e in self.entries.items() if e.page == page}
        while True:
            grew = {h for h, e in self.entries.items()
                    if e.parent in doomed and h not in doomed}
            if not grew:
                break
            doomed |= grew
        for h in doomed:
            self._drop_entry(h)
        for sid in [sid for sid, s in self.sessions.items()
                    if page in s.pages]:
            self.drop_session(sid)
        return before - self._holds.get(page, 0)

    # ------------------------------------------------------------ misc
    @property
    def n_entries(self) -> int:
        return len(self.entries)

    @property
    def n_sessions(self) -> int:
        return len(self.sessions)

    def forget(self):
        """Drop all bookkeeping WITHOUT touching table refcounts — the
        ``_fail_all`` companion to ``PageTable.reset()``, which already
        zeroed them."""
        self.entries.clear()
        self.sessions.clear()
        self._holds.clear()

    def report(self) -> dict:
        return {"entries": self.n_entries, "sessions": self.n_sessions,
                "cached_pages": self.cached_pages,
                "prefix_hits": self.hits,
                "prefix_hit_tokens": self.hit_tokens,
                "cow_copies": self.cow_copies,
                "evictions": self.evictions}
