"""Per-layer KV cache for the transformer serving plane — dense slots
and block-paged pages (ISSUE 14).

**Dense layout** (the original μ-cuDNN static slotting): one cache
serves one fixed pool of decode SLOTS, each preallocated to ``max_len``
rows. Layout mirrors the model's stacked-block parameterization so a
``lax.scan`` over layers can consume and re-emit the cache
layer-by-layer:

    {"k":   (L, n_slots, max_len, H, Dh)   compute dtype,
     "v":   (L, n_slots, max_len, H, Dh)   compute dtype,
     "pos": (n_slots,)                     int32}

``pos[s]`` is the number of tokens already resident in slot ``s`` —
equivalently the index the NEXT token's k/v will be written at, and the
inclusive upper bound of the attention mask for that slot. The cache is
a plain pytree: the engine's jitted ``decode_step`` donates it, so the
HBM buffers are updated in place across the whole decode loop and the
allocation cost is paid once per pool, not per token.

**Paged layout** (ISSUE 14 — the fix for the measured 96% waste of
dense slotting under mixed-length traffic): the pool is a fixed set of
fixed-size PAGES shared by every slot, plus a per-slot page table of
device gather indices:

    {"k":     (L, n_pages, page_len, H, Dh)      compute dtype,
     "v":     (L, n_pages, page_len, H, Dh)      compute dtype,
     "pos":   (n_slots,)                          int32,
     "pages": (n_slots, pages_per_slot)           int32}

``pages[s, j]`` is the pool page holding slot ``s``'s tokens
``[j*page_len, (j+1)*page_len)``; unmapped entries hold the sentinel
``n_pages`` (one past the pool) so a stray gather CLAMPS to masked
garbage and a stray scatter DROPS — a freed lane can never corrupt a
neighbour's live page. The page table is fixed-width
(``pages_per_slot = ceil(max_len / page_len)``), so the attention
gather shape is static and page-table GROWTH never retraces: mapping a
new page is a data change, not a shape change.

A short request holds ``ceil(len/page_len)`` pages instead of
``max_len`` rows, so the byte budget buys concurrency proportional to
*actual* token residency. The host side of the mapping lives in
:class:`PageTable` (free list + numpy mirror of ``pages``); the device
side rides the cache pytree through the same donated entry points as
the dense cache.

``DEFAULT_PAGE_LEN = 16`` follows the vLLM block-size precedent and the
``serving_page_len:*`` autotune cost records (``serving/tune.py``
re-measures it per shape/dtype/backend into the persistent autotune
cache).
"""

from __future__ import annotations

from typing import List

import numpy as np

import jax.numpy as jnp

# page size (tokens) — vLLM-style small blocks keep per-request
# over-allocation under one page; re-derived per shape/backend by
# serving.tune.sweep_serving_knobs into the autotune disk cache
DEFAULT_PAGE_LEN = 16
# prompt tokens one chunked-prefill dispatch processes (ISSUE 14):
# small enough that one chunk costs about one decode sweep (the ITL
# interleave contract), large enough to amortize dispatch — re-measured
# per shape/backend by the serving_prefill_chunk autotune records
DEFAULT_PREFILL_CHUNK = 128


def init_cache(cfg, n_slots: int, max_len=None, dtype=None):
    """Allocate an empty cache for ``n_slots`` concurrent sequences.

    ``max_len`` defaults to ``cfg.max_seq`` and may not exceed it: the
    learned position table has ``cfg.max_seq`` rows, so a longer cache
    would hold positions the model cannot embed.
    """
    max_len = int(cfg.max_seq if max_len is None else max_len)
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds cfg.max_seq={cfg.max_seq}: the "
            "position-embedding table has no rows past max_seq")
    if max_len < 1 or n_slots < 1:
        raise ValueError(f"need max_len >= 1 and n_slots >= 1, got "
                         f"max_len={max_len}, n_slots={n_slots}")
    dt = cfg.dtype if dtype is None else dtype
    shape = (cfg.n_layers, int(n_slots), max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((int(n_slots),), jnp.int32)}


def is_paged(cache) -> bool:
    """True for the block-paged layout (ISSUE 14)."""
    return "pages" in cache


def cache_len(cache) -> int:
    """Static per-slot capacity (tokens). For a paged cache this is the
    page-table ceiling ``pages_per_slot * page_len`` — what one slot
    could address if it mapped every entry, NOT what it has mapped."""
    if is_paged(cache):
        return cache["pages"].shape[1] * cache["k"].shape[2]
    return cache["k"].shape[2]


def cache_slots(cache) -> int:
    """Number of decode slots the cache was allocated for."""
    return cache["pos"].shape[0]


def page_len(cache) -> int:
    """Tokens per page (paged layout only)."""
    return cache["k"].shape[2]


def n_pages(cache) -> int:
    """Pool pages (paged layout only)."""
    return cache["k"].shape[1]


def pages_per_slot(cache) -> int:
    """Page-table width (paged layout only)."""
    return cache["pages"].shape[1]


def cache_nbytes(cache) -> int:
    """Total device bytes held by the cache (capacity planning: at the
    flagship 120M config a T=1024 slot is L8·T1024·H8·Dh64 · 2 tensors
    · 2 bytes = 16 MiB). For a paged cache this is the fixed POOL
    footprint — what the device actually reserves, regardless of how
    many pages are mapped."""
    return int(sum(a.size * a.dtype.itemsize for a in cache.values()))


def token_nbytes(cache) -> int:
    """Bytes ONE resident token occupies in one slot: k + v rows across
    every layer (shape positions are shared by both layouts). Resident
    tokens × token_nbytes vs the allocated bytes is the KV residency
    accounting (ISSUE 12/14): dense waste is the ``max_len - resident``
    tail a short request preallocates; paged waste is only the unfilled
    remainder of the LAST mapped page."""
    layers, _, _, heads, head_dim = cache["k"].shape
    return int(2 * layers * heads * head_dim * cache["k"].dtype.itemsize)


def page_nbytes(cache) -> int:
    """Bytes one PAGE holds across every layer (paged layout)."""
    return page_len(cache) * token_nbytes(cache)


def init_paged_cache(cfg, n_slots: int, n_pages: int,
                     page_len: int = DEFAULT_PAGE_LEN, max_len=None,
                     dtype=None):
    """Allocate an empty block-paged pool: ``n_pages`` shared pages of
    ``page_len`` tokens each, a per-slot cursor, and a per-slot page
    table sized ``ceil(max_len / page_len)`` entries (initially all the
    ``n_pages`` sentinel = unmapped). ``max_len`` bounds what ONE slot
    may address (defaults to ``cfg.max_seq``, same rule as the dense
    cache); the pool itself may hold far fewer than
    ``n_slots * max_len`` tokens — that is the point."""
    max_len = int(cfg.max_seq if max_len is None else max_len)
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds cfg.max_seq={cfg.max_seq}: the "
            "position-embedding table has no rows past max_seq")
    if page_len < 1 or n_pages < 1 or n_slots < 1 or max_len < 1:
        raise ValueError(
            f"need page_len/n_pages/n_slots/max_len >= 1, got "
            f"page_len={page_len}, n_pages={n_pages}, n_slots={n_slots}, "
            f"max_len={max_len}")
    per_slot = -(-max_len // int(page_len))          # ceil
    dt = cfg.dtype if dtype is None else dtype
    shape = (cfg.n_layers, int(n_pages), int(page_len), cfg.n_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((int(n_slots),), jnp.int32),
            "pages": jnp.full((int(n_slots), per_slot), int(n_pages),
                              jnp.int32)}


class PageTable:
    """Host side of the paged mapping: the free list and the numpy
    mirror of the device ``pages`` table. The scheduler maps pages
    before a dispatch needs them and releases them when a request
    finishes / is preempted / is cancelled; :meth:`device_table` hands
    the mirror to the device only when it changed (a (n_slots, P) int32
    transfer — never a retrace, the shape is fixed).

    Invariants (``check()`` asserts them; the fuzz test hammers them):
    a page is FREE xor mapped by exactly ONE slot, and
    ``free + mapped == n_pages`` always.
    """

    def __init__(self, n_slots: int, n_pages: int, page_len: int,
                 pages_per_slot: int):
        self.n_slots = int(n_slots)
        self.n_pages = int(n_pages)
        self.page_len = int(page_len)
        self.pages_per_slot = int(pages_per_slot)
        # pop() from the end → pages hand out in increasing id order
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self.table = np.full((self.n_slots, self.pages_per_slot),
                             self.n_pages, np.int32)
        self.mapped = np.zeros((self.n_slots,), np.int32)
        self._dirty = True                    # device mirror stale?

    @classmethod
    def for_cache(cls, cache) -> "PageTable":
        return cls(cache_slots(cache), n_pages(cache), page_len(cache),
                   pages_per_slot(cache))

    # ------------------------------------------------------- geometry
    def pages_for(self, tokens: int) -> int:
        """Pages required to hold ``tokens`` rows."""
        return -(-max(0, int(tokens)) // self.page_len)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def mapped_pages(self) -> int:
        return int(self.mapped.sum())

    def slot_tokens_capacity(self, slot: int) -> int:
        """Tokens the slot's mapped pages can hold right now."""
        return int(self.mapped[slot]) * self.page_len

    # -------------------------------------------------------- mapping
    def can_map(self, slot: int, tokens: int) -> bool:
        need = self.pages_for(tokens) - int(self.mapped[slot])
        return need <= len(self._free)

    def map(self, slot: int, tokens: int) -> bool:
        """Grow ``slot``'s mapping to cover ``tokens`` rows. All-or-
        nothing: returns False (mapping untouched) when the free list
        cannot cover the growth — the caller preempts to make room."""
        want = self.pages_for(tokens)
        if want > self.pages_per_slot:
            raise ValueError(
                f"slot {slot} wants {want} pages "
                f"({tokens} tokens), page table holds "
                f"{self.pages_per_slot}")
        have = int(self.mapped[slot])
        need = want - have
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for j in range(have, want):
            self.table[slot, j] = self._free.pop()
        self.mapped[slot] = want
        self._dirty = True
        return True

    def release(self, slot: int) -> int:
        """Return every page ``slot`` holds to the free list and reset
        its table row to the sentinel (so stale device writes from the
        freed lane DROP instead of landing in a re-issued page).
        Returns the number of pages released."""
        have = int(self.mapped[slot])
        if have == 0:
            return 0
        for j in range(have - 1, -1, -1):     # LIFO: reuse hot pages
            self._free.append(int(self.table[slot, j]))
        self.table[slot, :have] = self.n_pages
        self.mapped[slot] = 0
        self._dirty = True
        return have

    def reset(self):
        """Release everything (``_fail_all``)."""
        self._free = list(range(self.n_pages - 1, -1, -1))
        self.table[:] = self.n_pages
        self.mapped[:] = 0
        self._dirty = True

    # --------------------------------------------------------- device
    def sync(self, cache):
        """Refresh the cache's device ``pages`` from the host mirror iff
        the mapping changed since the last sync. The engine's entry
        points DONATE the cache — including the pages buffer — so the
        live device table always travels inside the cache pytree; this
        uploads a fresh (n_slots, P) int32 array only on change (a tiny
        transfer, fixed shape — page growth is data, never a
        retrace)."""
        if self._dirty:
            cache = dict(cache, pages=jnp.asarray(self.table))
            self._dirty = False
        return cache

    # ------------------------------------------------------ invariant
    def check(self):
        """Assert the free-xor-mapped-once invariant; raises
        AssertionError with a diagnosis on violation (the fuzz
        harness's oracle)."""
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate page in free list"
        seen = {}
        for s in range(self.n_slots):
            m = int(self.mapped[s])
            for j in range(self.pages_per_slot):
                p = int(self.table[s, j])
                if j < m:
                    assert 0 <= p < self.n_pages, \
                        f"slot {s} entry {j} unmapped below mapped count"
                    assert p not in free, \
                        f"page {p} mapped by slot {s} AND free"
                    assert p not in seen, \
                        f"page {p} double-mapped: slots {seen[p]}, {s}"
                    seen[p] = s
                else:
                    assert p == self.n_pages, \
                        f"slot {s} entry {j} holds {p} past mapped count"
        assert len(seen) + len(free) == self.n_pages, \
            f"lost pages: {self.n_pages - len(seen) - len(free)}"
        return True

    def report(self) -> dict:
        return {"n_pages": self.n_pages, "page_len": self.page_len,
                "pages_per_slot": self.pages_per_slot,
                "mapped_pages": self.mapped_pages,
                "free_pages": self.free_pages}
