"""Preallocated per-layer KV cache for the transformer serving plane.

One cache serves one fixed pool of decode SLOTS. Layout mirrors the
model's stacked-block parameterization so a ``lax.scan`` over layers can
consume and re-emit the cache layer-by-layer:

    {"k":   (L, n_slots, max_len, H, Dh)   compute dtype,
     "v":   (L, n_slots, max_len, H, Dh)   compute dtype,
     "pos": (n_slots,)                     int32}

``pos[s]`` is the number of tokens already resident in slot ``s`` —
equivalently the index the NEXT token's k/v will be written at, and the
inclusive upper bound of the attention mask for that slot. The cache is
a plain pytree: the engine's jitted ``decode_step`` donates it, so the
HBM buffers are updated in place across the whole decode loop and the
allocation cost is paid once per pool, not per token.

Fixed ``max_len`` by design (μ-cuDNN-style static slotting): admission
slices variable-length traffic into fixed-capacity slots instead of
reshaping device buffers per request — the scheduler keeps the sweep
full, the compiler sees one shape.
"""

from __future__ import annotations

import jax.numpy as jnp


def init_cache(cfg, n_slots: int, max_len=None, dtype=None):
    """Allocate an empty cache for ``n_slots`` concurrent sequences.

    ``max_len`` defaults to ``cfg.max_seq`` and may not exceed it: the
    learned position table has ``cfg.max_seq`` rows, so a longer cache
    would hold positions the model cannot embed.
    """
    max_len = int(cfg.max_seq if max_len is None else max_len)
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds cfg.max_seq={cfg.max_seq}: the "
            "position-embedding table has no rows past max_seq")
    if max_len < 1 or n_slots < 1:
        raise ValueError(f"need max_len >= 1 and n_slots >= 1, got "
                         f"max_len={max_len}, n_slots={n_slots}")
    dt = cfg.dtype if dtype is None else dtype
    shape = (cfg.n_layers, int(n_slots), max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((int(n_slots),), jnp.int32)}


def cache_len(cache) -> int:
    """Static per-slot capacity (tokens)."""
    return cache["k"].shape[2]


def cache_slots(cache) -> int:
    """Number of decode slots the cache was allocated for."""
    return cache["k"].shape[1]


def cache_nbytes(cache) -> int:
    """Total device bytes held by the cache (capacity planning: at the
    flagship 120M config a T=1024 slot is L8·T1024·H8·Dh64 · 2 tensors
    · 2 bytes = 16 MiB)."""
    return int(sum(a.size * a.dtype.itemsize for a in cache.values()))


def token_nbytes(cache) -> int:
    """Bytes ONE resident token occupies in one slot: k + v rows across
    every layer. ``resident tokens × token_nbytes`` vs ``cache_nbytes``
    is the KV residency accounting (ISSUE 12) — the number that sizes
    the paged-KV cache PR (ROADMAP item 1): waste is exactly the
    ``(max_len - resident) × token_nbytes`` a short request pays under
    fixed slotting."""
    layers, _, _, heads, head_dim = cache["k"].shape
    return int(2 * layers * heads * head_dim * cache["k"].dtype.itemsize)
