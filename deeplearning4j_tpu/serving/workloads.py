"""Multi-workload request plane (ISSUE 20): typed serving requests.

The scheduler (ROADMAP item 6) served exactly one scenario —
stochastic/greedy continuation — while every enabling mechanism for
the rest already existed: chunked prefill for prefill-only work
(PR 14), ref-counted CoW pages that let beams share their prefix for
free (PR 16), and the fidelity oracle that gates every new path
(PR 13). This module names the workloads and carries their results:

- :class:`RequestKind` — the enum ``submit(kind=...)`` and the fleet
  SUBMIT frames carry (one wire byte; see ``parallel/transport.py``):

  * ``GENERATE`` — the existing continuation path, unchanged;
  * ``SCORE`` — prefill-only chunked passes returning per-token
    logprobs + sequence perplexity; consumes NO decode slot time
    (the request retires at its final prefill chunk);
  * ``EMBED`` — pooled last-layer hidden states (post-``ln_f``) via
    the engine's ``return_hidden`` prefill path; also prefill-only;
  * ``BEAM`` — width-k beam search over the paged pool: all beams
    ``map_shared`` the root's prefix pages and CoW-split only on
    divergence, so k beams of length T cost ≈ T + k·divergent
    resident pages, not k·T (``PageTable.check()`` asserts it);
  * ``CONSTRAINED`` — per-request token mask (vocab allowlist or a
    grammar-step callback) applied inside a pre-warmed masked
    ``sample_tokens`` variant — zero retraces.

- result dataclasses (:class:`ScoreResult`, :class:`EmbedResult`,
  :class:`BeamResult`) that each expose ``tokens``/``finish_reason``
  so the fleet result frames and SLO close-out treat every kind
  uniformly;
- :class:`BeamState`, the scheduler's host-side beam-group record;
- :func:`vocab_mask`, the allowlist → bool-mask helper.

Equivalence oracles (tests/test_workloads.py): SCORE logprobs match
the full forward at every position; BEAM width-1 is bit-identical to
``GenerationEngine.generate``; a CONSTRAINED all-true mask is
bit-identical to greedy and every sampled token lies inside the mask
under fuzz; the beam page census shows shared-prefix residency.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Union

import numpy as np


class RequestKind(enum.Enum):
    """The typed request plane. Values are the human-facing strings
    (``summary()["kind"]``, metric labels); :attr:`wire` is the single
    byte the fleet SUBMIT frame carries."""

    GENERATE = "generate"
    SCORE = "score"
    EMBED = "embed"
    BEAM = "beam"
    CONSTRAINED = "constrained"

    @property
    def wire(self) -> int:
        return _KIND_WIRE[self]

    @classmethod
    def coerce(cls, value) -> "RequestKind":
        """Accept a RequestKind, its string value (case-insensitive),
        or its wire byte — the three spellings submit(), the fleet
        frames and the tests use."""
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            try:
                return cls(value.lower())
            except ValueError:
                raise ValueError(
                    f"unknown request kind {value!r}; expected one of "
                    f"{[k.value for k in cls]}") from None
        if isinstance(value, (int, np.integer)):
            try:
                return _WIRE_KIND[int(value)]
            except KeyError:
                raise ValueError(
                    f"unknown request-kind wire byte {int(value)}"
                ) from None
        raise ValueError(f"cannot coerce {type(value).__name__} to "
                         "RequestKind")


_KIND_WIRE = {RequestKind.GENERATE: 0, RequestKind.SCORE: 1,
              RequestKind.EMBED: 2, RequestKind.BEAM: 3,
              RequestKind.CONSTRAINED: 4}
_WIRE_KIND = {v: k for k, v in _KIND_WIRE.items()}

#: every kind value, in wire order — the census/gauge vocabulary
ALL_KINDS = tuple(k.value for k in sorted(RequestKind,
                                          key=lambda k: k.wire))

#: EMBED pooling modes and their wire bytes
POOLING_WIRE = {"mean": 0, "last": 1}
WIRE_POOLING = {v: k for k, v in POOLING_WIRE.items()}

#: a CONSTRAINED mask: a fixed (V,) bool allow-array, or a callback
#: ``step(generated_ids: List[int]) -> (V,) bool array`` consulted
#: before every sampled token (grammar stepping). Callbacks cannot
#: cross the fleet wire — only fixed allowlists do.
TokenMask = Union[np.ndarray, Callable[[List[int]], np.ndarray]]


def vocab_mask(allowed_ids, vocab_size: int) -> np.ndarray:
    """(V,) bool mask admitting exactly ``allowed_ids``."""
    ids = np.asarray(allowed_ids, np.int64).reshape(-1)
    if ids.size == 0:
        raise ValueError("empty allowlist would mask every token")
    if ids.min() < 0 or ids.max() >= vocab_size:
        raise ValueError(
            f"allowlist ids outside [0, {vocab_size})")
    mask = np.zeros((vocab_size,), bool)
    mask[ids] = True
    return mask


def resolve_mask(mask: TokenMask, generated: List[int],
                 vocab_size: int) -> np.ndarray:
    """The (V,) bool mask for the NEXT sampled token: fixed arrays
    pass through (validated once at submit), callbacks are consulted
    with the tokens generated so far."""
    m = mask(list(generated)) if callable(mask) else mask
    m = np.asarray(m, bool).reshape(-1)
    if m.shape != (vocab_size,):
        raise ValueError(f"token mask shape {m.shape} != "
                         f"({vocab_size},)")
    if not m.any():
        raise ValueError("token mask admits no token")
    return m


# --------------------------------------------------------------------------
# Result payloads — each carries tokens/finish_reason so the fleet
# result frames and the SLO close-out treat every kind uniformly
# --------------------------------------------------------------------------

@dataclass
class ScoreResult:
    """SCORE verdict: ``logprobs[i]`` is log P(prompt[i+1] | prompt[:i+1])
    — length ``len(prompt) - 1`` (position 0 is unconditional and
    skipped); ``perplexity = exp(-mean(logprobs))``."""
    logprobs: np.ndarray
    perplexity: float
    prompt_tokens: int
    finish_reason: str = "complete"
    ttft_s: Optional[float] = None
    latency_s: float = 0.0
    prefill_s: float = 0.0
    tokens: np.ndarray = field(
        default_factory=lambda: np.zeros((0,), np.int32))

    @property
    def total_logprob(self) -> float:
        return float(np.sum(self.logprobs))


@dataclass
class EmbedResult:
    """EMBED verdict: the pooled post-``ln_f`` last-layer hidden state,
    f32 ``(d_model,)``. ``pooling`` is "mean" (token-average) or
    "last" (final position's row)."""
    embedding: np.ndarray
    pooling: str
    prompt_tokens: int
    finish_reason: str = "complete"
    ttft_s: Optional[float] = None
    latency_s: float = 0.0
    prefill_s: float = 0.0
    tokens: np.ndarray = field(
        default_factory=lambda: np.zeros((0,), np.int32))


@dataclass
class BeamResult:
    """BEAM verdict: hypotheses sorted by total logprob, best first.
    ``tokens`` is the best sequence (prompt excluded) so the generic
    result plumbing — fleet frames, SLO token counts — reads a beam
    result exactly like a generation."""
    sequences: List[np.ndarray]
    scores: List[float]
    beam_width: int
    finish_reason: str = "length"
    ttft_s: Optional[float] = None
    latency_s: float = 0.0
    prefill_s: float = 0.0

    @property
    def tokens(self) -> np.ndarray:
        return self.sequences[0] if self.sequences else \
            np.zeros((0,), np.int32)

    @property
    def best_logprob(self) -> float:
        return self.scores[0] if self.scores else float("-inf")


@dataclass
class BeamState:
    """Host-side record of one live beam group (scheduler internal).
    ``slots[i]`` is the decode slot serving live beam ``i``;
    ``tokens[i]``/``scores[i]`` its generated ids and total logprob.
    ``done`` collects hypotheses that hit EOS (their slots are released
    immediately — the width shrinks). ``expanded`` flips once the root
    prefill has fanned out into the k slots."""
    width: int
    slots: List[int] = field(default_factory=list)
    tokens: List[List[int]] = field(default_factory=list)
    scores: List[float] = field(default_factory=list)
    done: List[tuple] = field(default_factory=list)   # (ids, score)
    expanded: bool = False

    def progress(self) -> int:
        """Generated length (all live beams advance in lockstep)."""
        if self.tokens:
            return len(self.tokens[0])
        return max((len(ids) for ids, _ in self.done), default=0)
