"""deeplearning4j_tpu.serving — the inference/serving plane (ISSUE 10).

Three layers over the zoo Transformer-LM:

- :mod:`kvcache` — preallocated per-layer KV cache, fixed ``max_len``
  slots, position cursors; a plain donated pytree.
- :mod:`engine` — :class:`GenerationEngine`: jitted ``prefill`` (prompt →
  cache + last logits) and donated-cache single-token ``decode_step``,
  plus greedy/temperature/top-k :func:`sample_tokens` under an explicit
  PRNG key. Logit-equivalent to the full forward at every position
  (tests/test_serving.py).
- :mod:`scheduler` — :class:`ContinuousBatchingScheduler`: fixed decode
  slot pool, per-slot admission prefill interleaved with full-pool
  decode sweeps, optional starvation preemption, per-request futures,
  and ``dl4j_serving_*`` metrics on the unified telemetry plane.

Plus :class:`FunctionalInferenceModel`, the shim that lets
``ParallelInference`` dynamic-batch a pure-functional forward (BERT,
the LM) like any network.

The SLO plane (ISSUE 11) rides on the scheduler: per-request
``obs.RequestTrace`` lifecycle timelines (→ ``dl4j_serving_itl_seconds``
and span trees), a crash :class:`~..obs.FlightRecorder` black box
(``scheduler.flight_recorder.dump()``, ``GET /debug/serving`` /
``/debug/requests`` on the UI server), and rolling goodput/burn-rate
accounting via ``slo=SLOConfig(...)`` (re-exported here).

The paged serving plane (ISSUE 14) rides the same three layers: a
block-paged KV pool (``init_paged_cache`` + host-side
:class:`~.kvcache.PageTable`) that allocates MAPPED pages instead of
``max_len`` rows per slot, chunked prefill
(``GenerationEngine.prefill_chunk``) that the scheduler interleaves
with decode sweeps, and page-availability-based admission
(``ContinuousBatchingScheduler(..., page_len=16)``). Knob defaults are
measured, not guessed: ``serving.tune`` sweeps
page-len/prefill-chunk/decode-slots into the persistent autotune cost
records.

The fleet fabric (ISSUE 18) is the tier above: :class:`FleetRouter`
fronts N scheduler-wrapped replicas behind the ``parallel/transport.py``
fleet frames, leases every request on a ``RequestLeaseTable``
(exactly-once completion, death → re-prefill on a survivor), routes by
session/prefix affinity then least burn-rate, and the
:class:`Autoscaler` spawns/retires replicas on sustained ``dl4j_slo_*``
burn. :mod:`traffic` generates the open-loop Poisson episodes that
exercise it (``run_episode`` → ``slo_report.py --fleet``).

The quantization & speculation plane (ISSUE 19) shrinks the bytes the
decode sweep moves, behind the fidelity gate: :mod:`quant` quantizes
KV pages (int8 rows + per-row-per-head scales riding the page axis)
and the decode matvec weights (int8, bf16 compute), each promoted
per-shape-bucket only when the race holds ``kl_max`` under the
``fidelity_report.py --max-kl`` bound AND measures faster; :mod:`spec`
adds draft-verify speculative decoding (:class:`SpeculativeDecoder` —
``EngineDraft``/``NgramDraft`` propose, the target's ``verify_chunk``
judges all k in one dispatch, rejected pages roll back via
``PageTable.trim``) whose greedy output is bit-identical to plain
decode. Losers fall back silently, counted in
``dl4j_autotune_promotions_total``.

The multi-workload request plane (ISSUE 20) makes the scheduler a
multi-tenant front door: ``submit(kind=...)`` types every request as
GENERATE, SCORE (prefill-only per-token logprobs + perplexity), EMBED
(pooled post-``ln_f`` hidden state), BEAM (width-k beam search whose
beams CoW-share the prompt's pages) or CONSTRAINED (token-mask
decoding through a pre-warmed masked sampler — zero retraces). The
:class:`RequestKind` enum rides the fleet SUBMIT frame as one wire
byte, results come back as :class:`ScoreResult` /
:class:`EmbedResult` / :class:`BeamResult`, and the
``dl4j_workload_*`` counters + per-kind SLO goodput
(``slo_report.py``) account each kind separately.

Quickstart: ``zoo.transformer.generate(params, cfg, ids, 32)`` for a
one-shot, or README "Serving quickstart" for the scheduler loop and
"Fleet quickstart" for the router.
"""

from ..obs import SLOConfig, SLOTracker  # noqa: F401  (serving SLO plane)
from .adapter import FunctionalInferenceModel  # noqa: F401
from .engine import (DEFAULT_PREFILL_BUCKETS, GenerationEngine,  # noqa: F401
                     sample_tokens)
from .fleet import (Autoscaler, AutoscalerConfig, FleetResult,  # noqa: F401
                    FleetRouter, InProcessReplica)
from .kvcache import (DEFAULT_PAGE_LEN, DEFAULT_PREFILL_CHUNK,  # noqa: F401
                      PageTable, PrefixCache, cache_len, cache_nbytes,
                      cache_slots, init_cache, init_paged_cache, is_paged,
                      is_quantized, page_nbytes, token_nbytes)
from .quant import (decide_kv, decide_weights, quantize_rows,  # noqa: F401
                    quantized_params, race_kv, race_weights)
from .scheduler import (ContinuousBatchingScheduler,  # noqa: F401
                        GenerationResult, ServingRequest)
from .spec import (EngineDraft, NgramDraft,  # noqa: F401
                   SpeculativeDecoder, race_spec)
from .traffic import (Arrival, EpisodeReport, TrafficConfig,  # noqa: F401
                      poisson_arrivals, run_episode)
from .workloads import (BeamResult, EmbedResult, RequestKind,  # noqa: F401
                        ScoreResult, vocab_mask)

__all__ = [
    "Arrival", "Autoscaler", "AutoscalerConfig", "BeamResult",
    "ContinuousBatchingScheduler", "DEFAULT_PAGE_LEN",
    "DEFAULT_PREFILL_BUCKETS", "DEFAULT_PREFILL_CHUNK", "EmbedResult",
    "EngineDraft", "EpisodeReport", "FleetResult", "FleetRouter",
    "FunctionalInferenceModel", "GenerationEngine", "GenerationResult",
    "InProcessReplica", "NgramDraft", "PageTable", "PrefixCache",
    "RequestKind", "SLOConfig", "SLOTracker", "ScoreResult",
    "ServingRequest", "SpeculativeDecoder", "TrafficConfig",
    "cache_len", "cache_nbytes", "cache_slots", "decide_kv",
    "decide_weights", "init_cache", "init_paged_cache", "is_paged",
    "is_quantized", "page_nbytes", "poisson_arrivals", "quantize_rows",
    "quantized_params", "race_kv", "race_spec", "race_weights",
    "run_episode", "sample_tokens", "token_nbytes", "vocab_mask",
]
