"""Serving-knob sweep into the persistent autotune cache (ISSUE 14).

The paged pool and chunked prefill add three tunables the dense plane
never had — ``page_len`` (tokens per KV page), ``prefill_chunk``
(prompt tokens per chunked-prefill dispatch), and the decode-sweep
slot count — and each trades the same way the μ-cuDNN micro-batch
size does: smaller buys granularity (less tail waste, shorter ITL
pauses), larger amortizes dispatch. Which side wins is a property of
the shape/dtype/BACKEND, so the verdict must be measured there, not
guessed here.

This module reuses the pallas block-size autotuner
(:mod:`..kernels.autotune`) as the measurement harness: every candidate
is timed on the real device with the marginal-chained-call discipline,
and the winner lands in ``~/.deeplearning4j_tpu/autotune.json`` as a
TVM-style cost record — ``{"choice": ..., "meta": {measured_at,
best_s, measurements: [[cand, seconds], ...]}}`` — keyed by
shape/dtype/backend. One sweep pays for every later run on the same
chip generation, and the records are the citable provenance for the
shipped defaults (``kvcache.DEFAULT_PAGE_LEN`` /
``DEFAULT_PREFILL_CHUNK``): :func:`recommended_serving_knobs` reads
the records back, choice + measurement meta together. This is the
first concrete brick of ROADMAP item 5's unified autotune harness —
serving knobs and pallas block sizes now share one cost-record store.

Keys (backend-qualified, like the flash-attention keys):

    serving_page_len:L{layers}H{heads}D{head_dim}:T{max_len}:S{slots}:{dtype}:{backend}
    serving_prefill_chunk:L{..}H{..}D{..}:T{prompt}:{dtype}:{backend}
    serving_decode_slots:L{..}H{..}D{..}:T{max_len}:{dtype}:{backend}

Run it:

    python -m deeplearning4j_tpu.serving.tune            # tiny default cfg
    from deeplearning4j_tpu.serving.tune import sweep_serving_knobs
    records = sweep_serving_knobs(engine)                # real engine

Sweep BEFORE ``engine.mark_warm()`` (or on a scratch engine): every
candidate is deliberately a new pool geometry / batch shape, so on a
warm-marked engine each one trips the retrace sentinel's warning and
counter — noise in a serve's zero-retrace gate, not a real storm.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

PAGE_LEN_CANDIDATES: Tuple[int, ...] = (8, 16, 32, 64)
PREFILL_CHUNK_CANDIDATES: Tuple[int, ...] = (32, 64, 128, 256)
DECODE_SLOT_CANDIDATES: Tuple[int, ...] = (2, 4, 8, 16)


def _key(kind: str, cfg, backend: str, **dims) -> str:
    tail = ":".join(f"{k}{v}" for k, v in dims.items())
    dt = getattr(cfg.dtype, "__name__", str(cfg.dtype))
    return (f"serving_{kind}:L{cfg.n_layers}H{cfg.n_heads}"
            f"D{cfg.head_dim}:{tail}:{dt}:{backend}")


def _backend() -> str:
    import jax
    return jax.default_backend()


def sweep_page_len(eng, *, slots: int = 4,
                   candidates: Sequence[int] = PAGE_LEN_CANDIDATES,
                   enabled: bool = True) -> int:
    """Time one paged decode sweep per candidate ``page_len`` and cache
    the winner. Each candidate's pool holds the SAME token budget
    (``slots × max_len`` rows) so the comparison isolates the gather
    granularity, not the byte budget."""
    import jax.numpy as jnp
    import numpy as np
    from ..kernels.autotune import autotune
    from . import kvcache

    max_len = int(eng.max_len)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, eng.cfg.vocab_size, (slots,)),
                       jnp.int32)

    def make_run(cand):
        (plen,) = cand
        if plen > max_len:
            return None
        n_pages = slots * (-(-max_len // plen))
        cache = eng.init_paged_cache(slots, n_pages, plen)
        pt = kvcache.PageTable.for_cache(cache)
        for s in range(slots):
            # half-full slots (steady state), with headroom mapped so
            # the timed steps' writes land in live pages
            pt.map(s, min(max_len, max_len // 2 + 64))
        cache = pt.sync(cache)
        cache = dict(cache, pos=jnp.full((slots,), max_len // 2,
                                         jnp.int32))
        state = {"cache": cache}

        def run():
            logits, state["cache"] = eng.decode_step(state["cache"], toks)
            return logits
        return run

    key = _key("page_len", eng.cfg, _backend(), T=max_len, S=slots)
    choice = autotune(key, [(c,) for c in candidates], make_run,
                      enabled=enabled)
    return int(choice[0])


def sweep_prefill_chunk(eng, *, prompt_len: int = 512,
                        candidates: Sequence[int] = PREFILL_CHUNK_CANDIDATES,
                        enabled: bool = True) -> int:
    """Time a full chunked prefill of one ``prompt_len`` prompt per
    candidate chunk size and cache the winner. The metric is the whole
    admission's wall, so the dispatch-overhead-vs-granularity trade is
    measured end to end (ITL interleave quality rides on the same
    number: one chunk is one sweep's pause)."""
    import jax.numpy as jnp
    import numpy as np
    from ..kernels.autotune import autotune
    from . import kvcache
    from .engine import GenerationEngine

    prompt_len = int(min(prompt_len, eng.max_len))
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, eng.cfg.vocab_size, (prompt_len,)).astype(
        np.int32)
    plen = kvcache.DEFAULT_PAGE_LEN

    def make_run(cand):
        (chunk,) = cand
        if chunk > prompt_len:
            return None
        # chunk_len is engine geometry (it fixes the chunk buckets), so
        # each candidate gets its own engine sharing the same params —
        # compile cost is excluded by the harness's warmup call
        ce = GenerationEngine(eng.cfg, eng.params, max_len=eng.max_len,
                              prefill_buckets=eng.prefill_buckets,
                              prefill_chunk=chunk)
        n_pages = -(-prompt_len // plen) + 1
        cache = ce.init_paged_cache(1, n_pages, plen)
        pt = kvcache.PageTable.for_cache(cache)
        pt.map(0, prompt_len)
        state = {"cache": pt.sync(cache)}

        def run():
            logits = None
            state["cache"] = dict(state["cache"],
                                  pos=jnp.zeros((1,), jnp.int32))
            for start in range(0, prompt_len, chunk):
                n = min(chunk, prompt_len - start)
                logits, state["cache"] = ce.prefill_chunk(
                    state["cache"], prompt[start:start + n], 0,
                    start=start)
            return logits
        return run

    key = _key("prefill_chunk", eng.cfg, _backend(), T=prompt_len)
    choice = autotune(key, [(c,) for c in candidates], make_run,
                      enabled=enabled)
    return int(choice[0])


def sweep_decode_slots(eng, *, total_tokens: int = 32,
                       candidates: Sequence[int] = DECODE_SLOT_CANDIDATES,
                       enabled: bool = True) -> int:
    """Time decoding the SAME total token budget at each slot count
    (``total_tokens/slots`` sweeps of ``slots`` tokens) and cache the
    winner — the throughput-optimal sweep width for this shape/backend
    (what the goodput-vs-slots trade in the bench decode row sweeps)."""
    import jax.numpy as jnp
    import numpy as np
    from ..kernels.autotune import autotune

    max_len = int(eng.max_len)
    rng = np.random.default_rng(0)

    def make_run(cand):
        (slots,) = cand
        if slots > total_tokens:
            return None
        steps = max(1, total_tokens // slots)
        toks = jnp.asarray(rng.integers(0, eng.cfg.vocab_size, (slots,)),
                           jnp.int32)
        cache = eng.init_cache(slots)
        cache = dict(cache, pos=jnp.full((slots,), max_len // 2,
                                         jnp.int32))
        state = {"cache": cache}

        def run():
            logits = None
            for _ in range(steps):
                logits, state["cache"] = eng.decode_step(state["cache"],
                                                         toks)
            return logits
        return run

    key = _key("decode_slots", eng.cfg, _backend(), T=max_len)
    choice = autotune(key, [(c,) for c in candidates], make_run,
                      enabled=enabled)
    return int(choice[0])


def sweep_serving_knobs(eng, *, enabled: bool = True,
                        prompt_len: int = 512) -> Dict[str, int]:
    """Run all three sweeps; returns the chosen knobs. Each verdict is
    a persistent cost record — re-running is a cache hit."""
    return {
        "page_len": sweep_page_len(eng, enabled=enabled),
        "prefill_chunk": sweep_prefill_chunk(eng, prompt_len=prompt_len,
                                             enabled=enabled),
        "decode_slots": sweep_decode_slots(eng, enabled=enabled),
    }


def recommended_serving_knobs(cfg=None, *, max_len: Optional[int] = None
                              ) -> Dict[str, dict]:
    """Read the serving cost records back: {knob: {choice, meta}} for
    every ``serving_*`` key in the unified store (filtered to ``cfg``'s
    shape when given). This is how a default is CITED — the choice
    plus the measurements that reached it, never a bare constant.
    Reads through the public harness API (``records(kind=...)``,
    ISSUE 17) — the kind filter prefix-matches every ``serving_*``
    family in one call."""
    from ..kernels.autotune import records

    out: Dict[str, dict] = {}
    want = None
    if cfg is not None:
        # field-exact match: keys are ':'-delimited, and a bare
        # substring would let L2H4D16 claim L2H4D160's records
        want = f"L{cfg.n_layers}H{cfg.n_heads}D{cfg.head_dim}"
    for key, rec in records(kind="serving").items():
        fields = key.split(":")
        if want is not None and want not in fields:
            continue
        if max_len is not None and f"T{int(max_len)}" not in fields:
            continue
        out[key] = {"choice": rec["choice"], "meta": rec["meta"]}
    return out


def _main():
    """CLI: sweep a tiny CPU-friendly config (or the flagship shape
    with --flagship) and print the records."""
    import argparse
    import json

    import jax
    import jax.numpy as jnp

    from ..zoo import transformer as tfm
    from .engine import GenerationEngine

    ap = argparse.ArgumentParser(description="serving-knob autotune sweep")
    ap.add_argument("--flagship", action="store_true",
                    help="sweep the 120M bench shape (slow on CPU)")
    ap.add_argument("--prompt-len", type=int, default=512)
    args = ap.parse_args()
    if args.flagship:
        cfg = tfm.TransformerConfig(vocab_size=32000, d_model=512,
                                    n_heads=8, n_layers=8, d_ff=2048,
                                    max_seq=1024, dtype=jnp.bfloat16,
                                    remat=False)
    else:
        cfg = tfm.TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                                    n_layers=2, d_ff=128, max_seq=512,
                                    dtype=jnp.float32, remat=False,
                                    attn_scores_bf16=False)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    eng = GenerationEngine(cfg, params)
    knobs = sweep_serving_knobs(eng, prompt_len=args.prompt_len)
    print(json.dumps({"chosen": knobs,
                      "records": recommended_serving_knobs(cfg)},
                     indent=2))


if __name__ == "__main__":
    _main()
