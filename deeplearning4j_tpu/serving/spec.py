"""Speculative decoding (ISSUE 19): draft-verify generation over the
paged pool, with page-exact rollback.

The scheme is the classic two-model split: a cheap **draft** proposes
``k`` greedy tokens, and the target model verifies ALL of them in ONE
forward through the existing chunked-prefill body (``verify_chunk`` —
the same block math, head over every row). Row ``i`` of the verify
logits is the target's next-token distribution after proposal ``i``,
so the longest prefix of proposals matching the target's own argmax is
accepted wholesale, and on the first mismatch the target's argmax IS
the correction token — every round emits ``accepted + 1`` tokens for
one target dispatch (``accepted`` when the whole window matched). In
greedy token space the output is therefore BIT-IDENTICAL to the
non-speculative decode by construction; the promotion race pins it
anyway (fp reduction order could bite) along with the speed gate.

Rollback is the page-table operation the paged pool already prepared
for: verify wrote the whole window's k/v into the slot's mapped pages,
so rejecting a tail is ``PageTable.trim`` (drop the holds on pages
past the accepted length — shared pages survive via their other
holders) plus a host-side ``pos`` rewind. Stale rows inside the kept
page sit beyond ``pos``, where the attention mask never reads and the
next append overwrites in order — the same contract preemption/remap
has always relied on. ``PageTable.check()`` stays green after every
round (the fuzz tests hammer it).

Two draft implementations ship:

- :class:`EngineDraft` — a (smaller) zoo model with its own dense
  cache (``zoo.transformer.draft_params`` builds a layer-truncated one
  sharing embeddings/head with the target). Its cache rewinds the same
  way the target's does: accepted proposals are exactly the tokens the
  draft itself processed, so a rollback is just a cursor rewind.
- :class:`NgramDraft` — prompt-lookup speculation (the vLLM/HF
  "prompt lookup decoding" trick): propose the continuation of the
  longest recent suffix match in the generated-so-far ids. Free to
  propose, surprisingly strong on self-repeating output.

Promotion (:func:`race_spec`) is per-draft-arm and per-shape-bucket
through ``kernels/autotune.py``: an arm promotes only when its greedy
tokens are bit-identical to the plain decode's, accepted-tokens/step
beats 1, AND its median tokens/s wins; otherwise the verdict is a
silent fallback counted in ``dl4j_autotune_promotions_total``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..kernels import autotune
from . import kvcache

__all__ = ["EngineDraft", "NgramDraft", "SpeculativeDecoder",
           "race_spec", "spec_bucket_key"]


def _registry():
    from ..obs import get_registry
    return get_registry()


# ------------------------------------------------------------- drafts --

class EngineDraft:
    """Draft tokens from a (smaller) zoo model with its own dense
    1-slot cache. ``propose`` decodes greedily from the shared context;
    after the target accepts/rejects, the next ``propose`` observes the
    shorter context and rewinds its cursor — rows for accepted tokens
    were written by the draft's own decode of those very tokens, so
    they are already correct, and rejected rows sit beyond the cursor
    where the mask never reads."""

    name = "engine"

    def __init__(self, engine):
        self.engine = engine
        self.cache = None
        self._pos = 0

    def reset(self):
        self.cache = None
        self._pos = 0

    def propose(self, ids: Sequence[int], k: int) -> List[int]:
        eng = self.engine
        if self.cache is None:
            self.cache = eng.init_cache(1)
            prompt = np.asarray(ids[:-1], np.int32)
            _, self.cache = eng.prefill_slot(self.cache, prompt, 0)
            self._pos = len(ids) - 1
        want = len(ids) - 1
        if want != self._pos:
            if want > self._pos:
                raise ValueError(
                    f"draft cursor {self._pos} behind context {want}: "
                    "propose() must see every accepted token")
            # rollback: rewind the cursor; accepted rows match what the
            # draft wrote, rejected ones are masked garbage
            self.cache = dict(self.cache,
                              pos=jnp.full((1,), want, jnp.int32))
            self._pos = want
        out: List[int] = []
        last = int(ids[-1])
        for _ in range(k):
            logits, self.cache = eng.decode_step(
                self.cache, np.asarray([last], np.int32))
            last = int(np.argmax(np.asarray(logits, np.float32)[0]))
            out.append(last)
        self._pos += k
        return out


class NgramDraft:
    """Prompt-lookup speculation: find the longest suffix of the
    context (up to ``n`` tokens) that recurred earlier, and propose
    whatever followed it last time. Stateless — rollback costs
    nothing."""

    name = "ngram"

    def __init__(self, n: int = 3):
        self.n = max(1, int(n))

    def reset(self):
        pass

    def propose(self, ids: Sequence[int], k: int) -> List[int]:
        ids = list(ids)
        t = len(ids)
        for n in range(min(self.n, t - 1), 0, -1):
            suffix = ids[t - n:]
            # most recent earlier occurrence wins
            for i in range(t - n - 1, -1, -1):
                if ids[i:i + n] == suffix and i + n < t:
                    cont = ids[i + n:i + n + k]
                    if cont:
                        return (cont + [ids[-1]] * (k - len(cont)))[:k]
        return [ids[-1]] * k


# ------------------------------------------------------------ decoder --

class SpeculativeDecoder:
    """Greedy draft-verify generation for ONE request over a private
    paged pool. The target engine's ``verify_chunk`` judges ``k``
    proposals per round; rejected tails roll back via
    ``PageTable.trim`` + a pos rewind, refcount-exactly (``check()``
    holds after every round — the fuzz harness pins it).

    ``preempt()`` releases every page mid-flight (the scheduler fault
    the rollback contract must survive); ``resume()`` re-admits the
    accepted context through chunked prefill and generation continues
    bit-identically. ``cancel()`` is preempt without the comeback."""

    def __init__(self, engine, draft, *, k: int = 4,
                 page_len: int = kvcache.DEFAULT_PAGE_LEN,
                 n_pages: Optional[int] = None,
                 quantized: Optional[bool] = None):
        if k < 1:
            raise ValueError("need k >= 1 draft proposals per round")
        if k >= engine.chunk_len:
            raise ValueError(f"k={k} proposals need a verify chunk of "
                             f"k rows <= chunk_len={engine.chunk_len}")
        self.engine = engine
        self.draft = draft
        self.k = int(k)
        per_slot = -(-engine.max_len // int(page_len))
        self.n_pages = int(per_slot if n_pages is None else n_pages)
        self.page_len = int(page_len)
        self.cache = engine.init_paged_cache(1, self.n_pages, page_len,
                                             quantized=quantized)
        self.table = kvcache.PageTable.for_cache(self.cache)
        # round accounting (the bench row + dl4j_spec_* metrics)
        self.rounds = 0
        self.proposed = 0
        self.accepted = 0
        self.rollback_pages = 0
        self._ids: List[int] = []
        self._emitted: List[int] = []

    # ------------------------------------------------------- plumbing
    def _set_pos(self, pos: int):
        self.cache = dict(self.cache,
                          pos=jnp.full((1,), int(pos), jnp.int32))

    def _map_to(self, tokens: int):
        if not self.table.map(0, tokens):
            raise RuntimeError(
                f"speculation pool exhausted: {tokens} tokens need "
                f"{self.table.pages_for(tokens)} pages, "
                f"{self.table.free_pages} free")
        self.cache = self.table.sync(self.cache)

    def _prefill(self, ids: Sequence[int]):
        """Chunked prefill of ``ids`` into slot 0 (admission and the
        post-preemption re-prefill share this). Returns last logits."""
        eng = self.engine
        n = len(ids)
        self._map_to(n)
        logits = None
        for start in range(0, n, eng.chunk_len):
            chunk = np.asarray(ids[start:start + eng.chunk_len], np.int32)
            logits, self.cache = eng.prefill_chunk(self.cache, chunk, 0,
                                                   start)
        self.table.note_fill(0, n)
        return logits

    # ------------------------------------------------------ lifecycle
    def release(self):
        """Drop every page hold (finish/cancel/preempt tail)."""
        self.table.release(0)
        self.cache = self.table.sync(self.cache)
        self._set_pos(0)

    def cancel(self):
        """Abandon the request: pages back to the free list, state
        cleared. ``check()`` must hold right after — no leaked refs."""
        self.release()
        self._ids = []
        self._emitted = []
        if hasattr(self.draft, "reset"):
            self.draft.reset()

    def preempt(self):
        """Scheduler-fault simulation: lose every page mid-generation
        (accepted context survives host-side in ``self._ids``)."""
        self.release()

    def resume(self):
        """Re-admit after :meth:`preempt`: chunked re-prefill of the
        accepted context (all ids but the unwritten last), exactly the
        scheduler's resumable-re-prefill path."""
        if not self._ids:
            raise RuntimeError("nothing to resume: no accepted context")
        self._prefill(self._ids[:-1])

    # ----------------------------------------------------- generation
    def stats(self) -> Dict:
        emitted = len(self._emitted)
        return {
            "rounds": self.rounds,
            "proposed": self.proposed,
            "accepted": self.accepted,
            "rollback_pages": self.rollback_pages,
            # tokens per VERIFY dispatch (the first token is the
            # prefill's, not a round's) — the ISSUE 19 gate is > 1
            "accepted_per_step": ((emitted - 1) / self.rounds
                                  if self.rounds else 0.0),
        }

    def generate(self, prompt_ids, max_new_tokens: int = 32, *,
                 eos_id: Optional[int] = None,
                 fault_hook=None) -> np.ndarray:
        """Greedy speculative generation; returns generated ids
        (prompt excluded), bit-identical in token space to the plain
        greedy decode. ``fault_hook(round, decoder)`` — test-only —
        runs before each verify round and may preempt/cancel."""
        eng = self.engine
        prompt = [int(t) for t in np.asarray(prompt_ids, np.int32)
                  .reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens - 1 > eng.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) - 1 exceeds max_len={eng.max_len}")
        if hasattr(self.draft, "reset"):
            self.draft.reset()
        reg = _registry()
        c_rounds = reg.counter(
            "dl4j_spec_rounds_total",
            "Speculative verify rounds, by draft mode",
            labelnames=("mode",))
        c_proposed = reg.counter(
            "dl4j_spec_proposed_total",
            "Draft tokens proposed, by draft mode", labelnames=("mode",))
        c_accepted = reg.counter(
            "dl4j_spec_accepted_total",
            "Draft tokens the target accepted, by draft mode",
            labelnames=("mode",))
        c_rollback = reg.counter(
            "dl4j_spec_rollback_pages_total",
            "Page mappings rolled back on rejected speculation",
            labelnames=("mode",))
        mode = getattr(self.draft, "name", "draft")

        logits = self._prefill(prompt)
        t0 = int(np.argmax(np.asarray(logits, np.float32)))
        ids = prompt + [t0]
        emitted = [t0]
        self._ids, self._emitted = ids, emitted
        rnd = 0
        while len(emitted) < max_new_tokens and \
                (eos_id is None or emitted[-1] != eos_id):
            if fault_hook is not None:
                fault_hook(rnd, self)
                if not self._ids:          # hook cancelled us
                    break
            rnd += 1
            pos = len(ids) - 1             # resident rows
            r = min(self.k, max_new_tokens - len(emitted))
            drafts = [int(t) for t in self.draft.propose(ids, r)]
            self.proposed += r
            rows = [ids[-1]] + drafts[:r - 1]
            self._map_to(pos + r)
            logits_all, self.cache = eng.verify_chunk(self.cache, rows,
                                                      0, pos)
            g = np.argmax(np.asarray(logits_all, np.float32)[:r],
                          axis=-1)
            m = 0
            while m < r and drafts[m] == int(g[m]):
                m += 1
            new = drafts[:r] if m == r else drafts[:m] + [int(g[m])]
            self.accepted += m
            ids.extend(new)
            emitted.extend(new)
            # rollback the rejected tail: resident rows are everything
            # but the (never-written) newest token
            new_pos = len(ids) - 1
            freed = self.table.trim(0, new_pos)
            self.rollback_pages += freed
            self.cache = self.table.sync(self.cache)
            self._set_pos(new_pos)
            self.table.note_fill(0, new_pos)
            self.rounds += 1
            c_rounds.inc(mode=mode)
            c_proposed.inc(r, mode=mode)
            c_accepted.inc(m, mode=mode)
            if freed:
                c_rollback.inc(freed, mode=mode)
        if eos_id is not None and eos_id in emitted:
            emitted = emitted[:emitted.index(eos_id) + 1]
        self._emitted = emitted
        return np.asarray(emitted, np.int32)


# ---------------------------------------------------------- promotion --

def spec_bucket_key(cfg, draft_name: str, k: int,
                    backend: Optional[str] = None) -> str:
    import jax
    if backend is None:
        backend = jax.default_backend()
    return (f"spec_decode:L{cfg.n_layers}H{cfg.n_heads}D{cfg.head_dim}"
            f":{draft_name}:K{int(k)}:{backend}")


def spec_sha() -> str:
    """Source fingerprint for ``spec_decode:*`` cost records."""
    return autotune.source_sha(SpeculativeDecoder, EngineDraft,
                               NgramDraft)


def plain_generate(engine, prompt_ids, max_new_tokens: int, *,
                   page_len: int = kvcache.DEFAULT_PAGE_LEN):
    """The non-speculative baseline the race (and the bench row)
    compares against: greedy decode of one request over an identical
    private paged pool — chunked prefill + one decode_step per token.
    Returns (generated ids, seconds)."""
    prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
    per_slot = -(-engine.max_len // int(page_len))
    cache = engine.init_paged_cache(1, per_slot, page_len)
    table = kvcache.PageTable.for_cache(cache)
    start = time.perf_counter()
    n = len(prompt)
    table.map(0, n + max_new_tokens - 1)
    cache = table.sync(cache)
    logits = None
    for s in range(0, n, engine.chunk_len):
        chunk = prompt[s:s + engine.chunk_len]
        logits, cache = engine.prefill_chunk(cache, chunk, 0, s)
    out = [int(np.argmax(np.asarray(logits, np.float32)))]
    while len(out) < max_new_tokens:
        logits, cache = engine.decode_step(cache,
                                           np.asarray([out[-1]], np.int32))
        out.append(int(np.argmax(np.asarray(logits, np.float32)[0])))
    elapsed = time.perf_counter() - start
    table.release(0)
    return np.asarray(out, np.int32), elapsed


def race_spec(engine, drafts: Dict[str, object], prompt_ids,
              max_new_tokens: int = 64, *, k: int = 4,
              reps: int = 3) -> Dict:
    """Race each draft arm against the plain greedy decode on one
    prompt. An arm promotes only when its tokens are BIT-IDENTICAL to
    the baseline's, accepted-tokens/step > 1, and its median wall time
    wins; first promoted arm (best speedup) is the record's choice,
    otherwise the baseline, with the usual silent-fallback verdicts
    counted per arm in ``dl4j_autotune_promotions_total``."""
    import jax

    cfg = engine.cfg
    base_times = []
    base_tokens = None
    for _ in range(max(1, reps)):
        base_tokens, dt = plain_generate(engine, prompt_ids,
                                         max_new_tokens)
        base_times.append(dt)
    base_s = float(np.median(base_times))

    arms: Dict[str, Dict] = {}
    for name, draft in drafts.items():
        times = []
        toks = None
        stats = None
        dec = SpeculativeDecoder(engine, draft, k=k)
        for _ in range(max(1, reps)):
            dec.rounds = dec.proposed = dec.accepted = 0
            dec.rollback_pages = 0
            t0 = time.perf_counter()
            toks = dec.generate(prompt_ids, max_new_tokens)
            times.append(time.perf_counter() - t0)
            stats = dec.stats()
            dec.release()
        arm_s = float(np.median(times))
        identical = (toks is not None and base_tokens is not None
                     and len(toks) == len(base_tokens)
                     and bool(np.array_equal(toks, base_tokens)))
        accept = float(stats["accepted_per_step"]) if stats else 0.0
        if not identical:
            verdict = "fallback_fidelity"
        elif accept <= 1.0 or arm_s >= base_s:
            verdict = "fallback_slower"
        else:
            verdict = "promoted"
        arms[name] = {
            "verdict": verdict, "spec_s": arm_s, "base_s": base_s,
            "speedup": round(base_s / arm_s, 3) if arm_s > 0 else None,
            "accepted_per_step": round(accept, 3),
            "bit_identical": identical,
            "stats": stats,
        }
        key = spec_bucket_key(cfg, name, k)
        chosen = name if verdict == "promoted" else "plain"
        autotune.put(key, (chosen,),
                     meta=dict(arms[name], backend=jax.default_backend()),
                     sha=spec_sha())
        _registry().counter(
            "dl4j_autotune_promotions_total",
            "Fidelity-gated kernel-vs-XLA promotion races, by verdict",
            labelnames=("kernel", "verdict")).inc(
                kernel="spec_decode", verdict=verdict)
    best = None
    for name, a in arms.items():
        if a["verdict"] == "promoted" and \
                (best is None or a["speedup"] > arms[best]["speedup"]):
            best = name
    return {"choice": best or "plain", "base_s": base_s,
            "tokens": int(len(base_tokens)), "arms": arms,
            "backend": jax.default_backend()}
