"""Fleet serving fabric (ISSUE 18, ROADMAP item 2): replicated engines
behind a leased router with SLO-driven autoscaling.

The tier above the continuous-batching scheduler: a :class:`FleetRouter`
fronts N scheduler-wrapped :class:`~.engine.GenerationEngine` replicas.
Replica handles are in-process today, but every submit and every result
round-trips the ``parallel/transport.py`` fleet frames
(``KIND_FLEET_SUBMIT`` / ``KIND_FLEET_RESULT``), so the byte layout that
a socket-backed replica host needs later is exercised in tier-1 now.

Requests become leased work items on a
:class:`~..parallel.leases.RequestLeaseTable` — the serving sibling of
the training lease table, carrying over its exactly-once completion
contract unchanged:

- every caller future resolves exactly once, fed by whichever replica
  currently HOLDS the item's lease;
- a replica death mid-decode releases its leases and the router
  re-prefills each on a survivor (recompute, the same mechanism as
  scheduler preemption — greedy output is bit-identical to the
  single-engine oracle because prefill reproduces the interrupted
  decode's logits exactly);
- a ghost result from a presumed-dead replica whose lease was re-granted
  fails ``complete()`` and is dropped (``dl4j_fleet_ghost_results_total``).

Routing prefers AFFINITY — a ``session_id`` (ISSUE 16) or a shared
prompt prefix lands on the replica already holding those KV pages — and
falls back to least burn-rate (each replica's rolling
``dl4j_slo_burn_rate``), tie-broken by load. The :class:`Autoscaler`
closes the control loop: sustained burn above target (or deep queues)
spawns a replica, sustained calm drains one via the scheduler's
``drain()`` — in-flight requests finish, unstarted queue entries are
handed back and re-routed, no future fails.

The whole episode is black-boxed: a fleet-level
:class:`~..obs.FlightRecorder` (``replica="fleet"``) snapshots
live/target replica counts, burn and scale events, and ``dump()``
appends it plus every replica's recorder (live, dead and retired) into
ONE JSONL that ``scripts/slo_report.py --fleet`` replays into a
per-replica + fleet-total goodput table.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ..obs import FlightRecorder, SLOConfig, SLOTracker, get_registry
from ..parallel.leases import RequestLeaseTable
from ..parallel.transport import (KIND_FLEET_RESULT, KIND_FLEET_SUBMIT,
                                  pack_fleet_result, pack_fleet_submit,
                                  unpack_fleet_result, unpack_fleet_submit)
from . import workloads
from .engine import GenerationEngine
from .scheduler import ContinuousBatchingScheduler
from .workloads import (BeamResult, EmbedResult, RequestKind, ScoreResult,
                        WIRE_POOLING)


@dataclass
class FleetResult:
    """What a fleet caller's future resolves to. The typed request
    plane (ISSUE 20) rides the same frame for every kind — ``kind``
    says which of the per-kind payload fields is populated:
    ``logprobs`` (SCORE, the per-token logprob vector), ``embedding``
    (EMBED, the pooled hidden state) or ``best_logprob`` (BEAM, the
    winning hypothesis' total logprob — its ids are ``tokens``)."""
    tokens: np.ndarray          # generated ids, prompt excluded
    finish_reason: str          # "eos" | "length" | "complete"
    item: int                   # lease item id
    replica: str                # label of the replica that COMPLETED it
    reprefills: int             # times the lease moved (replica deaths)
    ttft_s: Optional[float]
    latency_s: float
    kind: str = "generate"      # RequestKind value string
    logprobs: Optional[np.ndarray] = None       # SCORE
    embedding: Optional[np.ndarray] = None      # EMBED
    best_logprob: Optional[float] = None        # BEAM


@dataclass(frozen=True)
class AutoscalerConfig:
    """SLO-driven scaling policy. Burn rate is the primary signal
    (sustained >1 means the quantile objective WILL be missed); queue
    depth per replica is the leading indicator that trips before a
    slow rolling window does."""
    min_replicas: int = 1
    max_replicas: int = 4
    high_burn: float = 1.0       # sustained above → pressure
    low_burn: float = 0.5        # below this (and queues calm) → calm
    high_queue: float = 4.0      # queued requests per replica → pressure
    patience: int = 3            # consecutive evals before acting
    cooldown: int = 4            # evals to hold after a scale event

    def __post_init__(self):
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if self.patience < 1:
            raise ValueError("patience must be >= 1")


class Autoscaler:
    """Hysteresis over the burn/queue signals: ``evaluate`` returns
    +1 (spawn), -1 (retire) or 0. Pure host-side state machine — the
    synthetic-burn unit tests drive it directly."""

    def __init__(self, config: Optional[AutoscalerConfig] = None):
        self.config = config or AutoscalerConfig()
        self._high = 0
        self._low = 0
        self._hold = 0
        self.events: List[str] = []     # "up"/"down" history

    def evaluate(self, burn: Optional[float], queue_per_replica: float,
                 n_live: int) -> int:
        cfg = self.config
        b = 0.0 if burn is None else float(burn)
        pressured = b > cfg.high_burn or queue_per_replica > cfg.high_queue
        calm = b < cfg.low_burn and queue_per_replica <= 1.0
        if pressured:
            self._high += 1
            self._low = 0
        elif calm:
            self._low += 1
            self._high = 0
        else:
            self._high = 0
            self._low = 0
        if self._hold > 0:
            self._hold -= 1
            return 0
        if self._high >= cfg.patience and n_live < cfg.max_replicas:
            self._high = 0
            self._hold = cfg.cooldown
            self.events.append("up")
            return 1
        if self._low >= cfg.patience and n_live > cfg.min_replicas:
            self._low = 0
            self._hold = cfg.cooldown
            self.events.append("down")
            return -1
        return 0


class InProcessReplica:
    """One scheduler-wrapped engine behind the fleet wire boundary.

    ``submit_frame`` takes a packed ``KIND_FLEET_SUBMIT`` payload and
    unpacks it replica-side — the router never hands this class a
    Python object a socket could not carry, so a host process speaking
    the same frames can replace it without touching the router."""

    def __init__(self, rid: int, engine: GenerationEngine, *,
                 n_slots: int = 4,
                 slo: Union[SLOConfig, SLOTracker, None] = None,
                 scheduler_kwargs: Optional[Dict[str, Any]] = None):
        self.rid = int(rid)
        self.replica = f"r{rid}"
        self.engine = engine
        self.status = "live"            # live | dead | retired
        self.scheduler = ContinuousBatchingScheduler(
            engine, n_slots=n_slots, replica=self.replica, slo=slo,
            **dict(scheduler_kwargs or {}))

    # ------------------------------------------------------ wire side
    def submit_frame(self, kind: int, payload: bytes) -> Future:
        if kind != KIND_FLEET_SUBMIT:
            raise ValueError(f"replica cannot serve frame kind {kind}")
        sub = unpack_fleet_submit(payload)
        kind = RequestKind.coerce(sub["kind"])
        # session retention needs the prefix cache; without it the
        # session id still steered AFFINITY router-side, which is all
        # a dense replica can honour
        sid = sub["session_id"] if getattr(
            self.scheduler, "_prefix", None) is not None else None
        kwargs: Dict[str, Any] = {}
        if kind is RequestKind.BEAM:
            kwargs["beam_width"] = sub["beam_width"]
        elif kind is RequestKind.EMBED:
            kwargs["pooling"] = WIRE_POOLING[sub["pooling"]]
        elif kind is RequestKind.CONSTRAINED:
            # the wire carries a fixed allowlist — grammar callbacks
            # cannot cross a socket, so the frame's mask vocabulary is
            # exactly vocab_mask (rebuilt replica-side against THIS
            # engine's vocab, which also re-validates the ids)
            kwargs["token_mask"] = workloads.vocab_mask(
                sub["allowed_ids"], int(self.engine.cfg.vocab_size))
        return self.scheduler.submit(
            sub["prompt_ids"], sub["max_new_tokens"],
            temperature=sub["temperature"], top_k=sub["top_k"] or 0,
            eos_id=sub["eos_id"], session_id=sid, kind=kind, **kwargs)

    @staticmethod
    def result_frame(item: int, result) -> Tuple[int, bytes]:
        """Pack any kind's result into ONE wire shape: ids + reason +
        kind byte + a per-kind float vector (SCORE's logprobs, EMBED's
        embedding, BEAM's best total logprob)."""
        kind, floats = RequestKind.GENERATE, None
        if isinstance(result, ScoreResult):
            kind, floats = RequestKind.SCORE, result.logprobs
        elif isinstance(result, EmbedResult):
            kind, floats = RequestKind.EMBED, result.embedding
        elif isinstance(result, BeamResult):
            kind, floats = RequestKind.BEAM, [result.best_logprob]
        return KIND_FLEET_RESULT, pack_fleet_result(
            item, result.tokens, result.finish_reason,
            kind=kind.wire, floats=floats)

    # ------------------------------------------------------ signals
    def burn_rate(self) -> Optional[float]:
        """This replica's burn rate, or None when there is NO FRESH
        evidence: the SLO window prunes by latest-observed timestamp,
        so a replica traffic moved away from would otherwise freeze at
        its last (possibly terrible) verdict forever — shunned by
        least-burn routing, pinning the autoscaler's max-burn signal
        high, and never refreshing. Staleness = no observation within
        ``window_s`` of wall clock."""
        slo = self.scheduler.slo
        if slo is None:
            return None
        b = slo.burn_rate()
        if b is None:
            return None
        if time.time() - slo.latest_ts > slo.config.window_s:
            return None
        return b

    def load(self) -> float:
        s = self.scheduler
        return s.queue_depth() + s.occupancy() * s.n_slots


@dataclass
class _Outstanding:
    """Router-side record of one leased request."""
    item: int
    payload: bytes              # the packed FLEET_SUBMIT frame, re-sent
    #                             verbatim on every re-route
    caller: Future
    session_id: Optional[str]
    prefix_key: bytes
    submitted_ts: float
    rid: int = -1
    replica_future: Optional[Future] = None
    reprefills: int = 0
    routed_reason: str = ""
    kind: str = "generate"      # RequestKind value (ISSUE 20)


class FleetRouter:
    """N replicas, one lease table, one front door.

    Synchronous core like the scheduler: ``step()`` steps every live
    replica, collects completions, and (periodically) runs the
    autoscaler; ``run_until_idle()`` loops it. ``submit()`` packs the
    request into a fleet frame, leases it, and routes it — the returned
    future NEVER hangs: replica death re-routes its leases, and if no
    live replica remains the future fails with the cause.

    ``engine`` may be a single :class:`GenerationEngine` shared by all
    replicas (each scheduler owns its own KV cache; in-process the
    jitted functions are stateless over the cache argument, so sharing
    skips per-replica compiles) or a zero-arg factory for
    one-engine-per-replica."""

    def __init__(self, engine: Union[GenerationEngine, Callable[[],
                 GenerationEngine]], *, n_replicas: int = 1,
                 n_slots: int = 4,
                 slo: Optional[SLOConfig] = None,
                 autoscaler: Union[Autoscaler, AutoscalerConfig,
                                   None] = None,
                 scheduler_kwargs: Optional[Dict[str, Any]] = None,
                 affinity_prefix_len: int = 16,
                 autoscale_every: int = 8,
                 snapshot_every: int = 16,
                 recorder_snapshots: int = 1024,
                 quant_kv: Optional[str] = None):
        if n_replicas < 1:
            raise ValueError("need at least one replica")
        if isinstance(engine, GenerationEngine):
            self._factory: Callable[[], GenerationEngine] = lambda: engine
        else:
            self._factory = engine
        self.n_slots = int(n_slots)
        self.slo = slo
        self._scheduler_kwargs = dict(scheduler_kwargs or {})
        # quant plumbing (ISSUE 19): the fleet-level mode reaches every
        # replica's scheduler — scale-out and scale-up replicas get the
        # same quantized pool (re-prefill after preemption re-quantizes
        # at append, so migration across replicas stays mode-blind)
        if quant_kv is not None:
            self._scheduler_kwargs["quant_kv"] = quant_kv
        self.affinity_prefix_len = int(affinity_prefix_len)
        self.autoscale_every = max(1, int(autoscale_every))
        self.snapshot_every = max(1, int(snapshot_every))
        if isinstance(autoscaler, Autoscaler):
            self.autoscaler: Optional[Autoscaler] = autoscaler
        elif autoscaler is not None:
            self.autoscaler = Autoscaler(autoscaler)
        else:
            self.autoscaler = None
        self.leases = RequestLeaseTable()
        self.outstanding: Dict[int, _Outstanding] = {}
        self.recorder = FlightRecorder(
            capacity_snapshots=recorder_snapshots, replica="fleet")
        self.replicas: Dict[int, InProcessReplica] = {}
        self._session_aff: Dict[str, int] = {}
        self._prefix_aff: Dict[bytes, int] = {}
        self._lock = threading.RLock()
        self._next_rid = 0
        self._steps = 0
        self._metrics = None
        self.ghost_results = 0
        self.reprefills = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.target_replicas = int(n_replicas)
        for _ in range(n_replicas):
            self._spawn_locked(reason="initial")

    # ------------------------------------------------------- metrics
    def _m(self):
        if self._metrics is None:
            reg = get_registry()
            self._metrics = {
                "live": reg.gauge(
                    "dl4j_fleet_replicas_live",
                    "Live replicas behind the fleet router"),
                "target": reg.gauge(
                    "dl4j_fleet_replicas_target",
                    "Autoscaler's current replica target"),
                "requests": reg.counter(
                    "dl4j_fleet_requests_total",
                    "Requests submitted to the fleet router"),
                "routed": reg.counter(
                    "dl4j_fleet_routed_total",
                    "Routing decisions, by reason (affinity = session/"
                    "prefix stickiness, least_burn = burn-rate pick, "
                    "drain = handed back by a retiring replica)",
                    labelnames=("reason",)),
                "reprefills": reg.counter(
                    "dl4j_fleet_reprefills_total",
                    "Leases re-prefilled on a survivor after replica "
                    "death"),
                "ghosts": reg.counter(
                    "dl4j_fleet_ghost_results_total",
                    "Results dropped because the sender no longer held "
                    "the lease (exactly-once accounting)"),
                "scale_events": reg.counter(
                    "dl4j_fleet_scale_events_total",
                    "Autoscaler actions, by direction",
                    labelnames=("direction",)),
            }
        return self._metrics

    # ------------------------------------------------------ replicas
    def _spawn_locked(self, reason: str = "scale_up") -> int:
        rid = self._next_rid
        self._next_rid += 1
        self.replicas[rid] = InProcessReplica(
            rid, self._factory(), n_slots=self.n_slots, slo=self.slo,
            scheduler_kwargs=self._scheduler_kwargs)
        self.recorder.record_snapshot(event="replica_spawn", rid=rid,
                                      reason=reason)
        self._export_replica_gauges_locked()
        return rid

    def _live_locked(self) -> List[InProcessReplica]:
        return [rep for _, rep in sorted(self.replicas.items())
                if rep.status == "live"]

    def _export_replica_gauges_locked(self):
        m = self._m()
        m["live"].set(float(len(self._live_locked())))
        m["target"].set(float(self.target_replicas))

    def n_live(self) -> int:
        with self._lock:
            return len(self._live_locked())

    # -------------------------------------------------------- submit
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None,
               session_id: Optional[str] = None,
               kind=RequestKind.GENERATE, beam_width: int = 0,
               pooling: str = "mean",
               allowed_ids=None) -> Future:
        """Lease + route one typed serving request (ISSUE 20); returns
        a Future resolving to a :class:`FleetResult` whose per-kind
        payload field matches ``kind``. CONSTRAINED over the wire is
        allowlist-only — ``allowed_ids`` packs into the frame and the
        replica rebuilds the vocab mask; grammar-step callbacks cannot
        cross a socket boundary (use the scheduler API directly for
        those). A kind survives replica death unchanged: the packed
        frame is re-sent verbatim, so the re-prefilled request is the
        same typed request."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        kind = RequestKind.coerce(kind)
        if kind is RequestKind.CONSTRAINED and allowed_ids is None:
            raise ValueError(
                "fleet constrained decoding needs allowed_ids (fixed "
                "allowlist; callbacks cannot cross the wire)")
        if allowed_ids is not None and kind is not RequestKind.CONSTRAINED:
            raise ValueError("allowed_ids is a CONSTRAINED knob "
                             f"(got kind={kind.value!r})")
        if pooling not in workloads.POOLING_WIRE:
            raise ValueError(f"unknown pooling {pooling!r}; expected "
                             f"one of {sorted(workloads.POOLING_WIRE)}")
        with self._lock:
            live = self._live_locked()
            if not live:
                raise RuntimeError("no live replicas")
            # validate against the engine contract BEFORE creating the
            # lease, so a rejected request never dangles in the table
            max_len = live[0].engine.max_len
            total = prompt.size if kind in (
                RequestKind.SCORE, RequestKind.EMBED) \
                else prompt.size + max_new_tokens - 1
            if total > max_len:
                raise ValueError(
                    f"prompt ({prompt.size}) + budget = {total} "
                    f"exceeds max_len={max_len}")
            item = self.leases.add()
            payload = pack_fleet_submit(
                item, prompt, max_new_tokens, temperature, top_k,
                eos_id, session_id, kind=kind.wire,
                beam_width=int(beam_width),
                pooling=workloads.POOLING_WIRE[pooling],
                allowed_ids=allowed_ids)
            rec = _Outstanding(
                item=item, payload=payload, caller=Future(),
                session_id=session_id,
                prefix_key=prompt[:self.affinity_prefix_len].tobytes(),
                submitted_ts=time.perf_counter(), kind=kind.value)
            self.outstanding[item] = rec
            self._m()["requests"].inc()
            self._route_locked(rec)
        return rec.caller

    # ------------------------------------------------------- routing
    def _pick_locked(self, rec: _Outstanding) -> Tuple[int, str]:
        live = self._live_locked()
        if not live:
            raise RuntimeError("no live replicas")
        live_ids = {rep.rid for rep in live}
        if rec.session_id is not None:
            rid = self._session_aff.get(rec.session_id)
            if rid in live_ids:
                return rid, "affinity"
        rid = self._prefix_aff.get(rec.prefix_key)
        if rid in live_ids:
            return rid, "affinity"
        inflight: Dict[int, int] = {}
        for o in self.outstanding.values():
            if o.replica_future is not None and not o.caller.done():
                inflight[o.rid] = inflight.get(o.rid, 0) + 1

        def cost(rep: InProcessReplica):
            burn = rep.burn_rate()
            return (0.0 if burn is None else burn,
                    rep.scheduler.queue_depth() + inflight.get(rep.rid, 0),
                    rep.rid)

        return min(live, key=cost).rid, "least_burn"

    def _route_locked(self, rec: _Outstanding, reason: Optional[str] = None):
        """Lease + dispatch ``rec`` onto a live replica; on total fleet
        loss the caller future FAILS rather than hangs."""
        m = self._m()
        try:
            rid, why = self._pick_locked(rec)
            if not self.leases.lease(rec.item, rid):
                raise RuntimeError(
                    f"lease {rec.item} not AVAILABLE at route time")
            rec.rid = rid
            rec.routed_reason = reason or why
            rec.replica_future = self.replicas[rid].submit_frame(
                KIND_FLEET_SUBMIT, rec.payload)
        except Exception as e:  # noqa: BLE001 — the never-hang contract
            self.outstanding.pop(rec.item, None)
            try:
                rec.caller.set_exception(e)
            except Exception:   # noqa: BLE001 — already resolved
                pass
            return
        m["routed"].inc(reason=rec.routed_reason)
        if rec.session_id is not None:
            self._session_aff[rec.session_id] = rid
        self._prefix_aff[rec.prefix_key] = rid

    # ------------------------------------------------------ stepping
    def step(self) -> bool:
        """One fleet iteration: step every live replica, collect
        completions, periodically autoscale + snapshot. Returns True if
        any work happened."""
        with self._lock:
            live = self._live_locked()
        did = False
        for rep in live:
            try:
                did = rep.scheduler.step() or did
            except Exception:   # noqa: BLE001 — a crashing replica is a
                # replica DEATH, not a fleet death: release + re-route
                self.kill_replica(rep.rid)
                did = True
        did = self._poll_completions() or did
        self._steps += 1
        if self.autoscaler is not None and \
                self._steps % self.autoscale_every == 0:
            self._autoscale()
        if self._steps % self.snapshot_every == 0:
            self._record_fleet_snapshot()
        return did

    def run_until_idle(self, max_steps: int = 200000):
        """Drive step() until every outstanding lease completed."""
        for _ in range(max_steps):
            with self._lock:
                idle = not self.outstanding
            if idle:
                return
            self.step()
        raise RuntimeError(f"fleet not idle after {max_steps} steps")

    def _poll_completions(self) -> bool:
        with self._lock:
            ready = [rec for rec in self.outstanding.values()
                     if rec.replica_future is not None
                     and rec.replica_future.done()]
        any_done = False
        m = self._m()
        for rec in ready:
            fut = rec.replica_future
            exc = fut.exception()
            with self._lock:
                if exc is not None:
                    # replica-side failure: the lease completes (the
                    # request was consumed) and the caller learns why
                    if self.leases.complete(rec.rid, rec.item):
                        self.outstanding.pop(rec.item, None)
                        try:
                            rec.caller.set_exception(exc)
                        except Exception:   # noqa: BLE001
                            pass
                        any_done = True
                    else:
                        self.ghost_results += 1
                        m["ghosts"].inc()
                    continue
                res = fut.result()
                # round-trip the result through the wire frame — the
                # boundary a socket host will speak
                _, payload = InProcessReplica.result_frame(rec.item, res)
                out = unpack_fleet_result(payload)
                if not self.leases.complete(rec.rid, rec.item):
                    self.ghost_results += 1     # exactly-once: dropped
                    m["ghosts"].inc()
                    continue
                self.outstanding.pop(rec.item, None)
                # per-kind float payload (ISSUE 20): the frame's kind
                # byte says how to read the vector; the ROUTER's record
                # names the caller-facing kind (a CONSTRAINED result
                # rides a generate-shaped frame)
                wire_kind = RequestKind.coerce(out["kind"])
                fl = out["floats"]
                result = FleetResult(
                    tokens=out["token_ids"],
                    finish_reason=out["reason"], item=rec.item,
                    replica=f"r{rec.rid}", reprefills=rec.reprefills,
                    ttft_s=res.ttft_s,
                    latency_s=time.perf_counter() - rec.submitted_ts,
                    kind=rec.kind,
                    logprobs=fl if wire_kind is RequestKind.SCORE
                    else None,
                    embedding=fl if wire_kind is RequestKind.EMBED
                    else None,
                    best_logprob=float(fl[0])
                    if wire_kind is RequestKind.BEAM and fl.size
                    else None)
            try:
                rec.caller.set_result(result)
            except Exception:   # noqa: BLE001 — caller cancelled
                pass
            any_done = True
        return any_done

    # ------------------------------------------------- fault / retire
    def kill_replica(self, rid: int) -> List[int]:
        """Simulate (or acknowledge) replica death: stop stepping it,
        release its leases, and RE-PREFILL each on a survivor — the
        recompute path, so greedy output is unchanged. Returns the item
        ids that moved."""
        with self._lock:
            rep = self.replicas.get(rid)
            if rep is None or rep.status != "live":
                return []
            rep.status = "dead"
            items = self.leases.release_replica(rid)
            m = self._m()
            for item in items:
                rec = self.outstanding.get(item)
                if rec is None:
                    continue
                rec.reprefills += 1
                self.reprefills += 1
                m["reprefills"].inc()
                self._route_locked(rec)
            self.recorder.record_snapshot(
                event="replica_dead", rid=rid, releases=len(items))
            self._export_replica_gauges_locked()
            return items

    def retire_replica(self, rid: int) -> int:
        """Graceful scale-down: drain the replica (in-flight requests
        FINISH on it), collect their completions, then re-route the
        unstarted queue entries it hands back. No caller future fails.
        Returns the number of entries re-routed."""
        with self._lock:
            rep = self.replicas.get(rid)
            if rep is None or rep.status != "live":
                return 0
            rep.status = "retired"      # out of routing + stepping
        # drain outside the router lock: it loops scheduler.step() —
        # real device work
        rep.scheduler.drain()
        self._poll_completions()        # harvest the drained finishes
        with self._lock:
            moved = 0
            for item in self.leases.release_replica(rid):
                rec = self.outstanding.get(item)
                if rec is None:
                    continue
                self._route_locked(rec, reason="drain")
                moved += 1
            self.recorder.record_snapshot(
                event="replica_retired", rid=rid, handed_back=moved)
            self._export_replica_gauges_locked()
            return moved

    # ---------------------------------------------------- autoscaler
    def _signals_locked(self) -> Tuple[Optional[float], float, int]:
        live = self._live_locked()
        n = len(live)
        burns = [b for b in (rep.burn_rate() for rep in live)
                 if b is not None]
        burn = max(burns) if burns else None
        total_q = sum(rep.scheduler.queue_depth() for rep in live)
        return burn, total_q / max(n, 1), n

    def _autoscale(self):
        with self._lock:
            burn, qpr, n = self._signals_locked()
            decision = self.autoscaler.evaluate(burn, qpr, n)
            if decision > 0:
                self.target_replicas = n + 1
                rid = self._spawn_locked(reason="burn")
                self.scale_ups += 1
                self._m()["scale_events"].inc(direction="up")
                self.recorder.record_snapshot(
                    event="scale", scale_event="up", rid=rid, burn=burn,
                    queue_per_replica=round(qpr, 3), replicas_live=n + 1,
                    replicas_target=self.target_replicas)
                return
            if decision < 0:
                victim = min(self._live_locked(),
                             key=lambda rep: (rep.load(), rep.rid))
                self.target_replicas = n - 1
        if decision < 0:
            self.retire_replica(victim.rid)
            self.scale_downs += 1
            self._m()["scale_events"].inc(direction="down")
            with self._lock:
                self.recorder.record_snapshot(
                    event="scale", scale_event="down", rid=victim.rid,
                    burn=burn, queue_per_replica=round(qpr, 3),
                    replicas_live=n - 1,
                    replicas_target=self.target_replicas)
                self._export_replica_gauges_locked()

    def _record_fleet_snapshot(self):
        with self._lock:
            burn, qpr, n = self._signals_locked()
            self.recorder.record_snapshot(
                step=self._steps, replicas_live=n,
                replicas_target=self.target_replicas,
                outstanding=len(self.outstanding),
                queue_per_replica=round(qpr, 3),
                burn=None if burn is None else round(burn, 4),
                reprefills=self.reprefills,
                scale_ups=self.scale_ups, scale_downs=self.scale_downs)

    # ------------------------------------------------------- reports
    def fleet_report(self) -> Dict[str, Any]:
        with self._lock:
            burn, qpr, n = self._signals_locked()
            reps = {}
            for rid, rep in sorted(self.replicas.items()):
                r: Dict[str, Any] = {"status": rep.status}
                if rep.status == "live":
                    r["queue_depth"] = rep.scheduler.queue_depth()
                    r["occupancy"] = rep.scheduler.occupancy()
                    b = rep.burn_rate()
                    if b is not None:
                        r["burn_rate"] = round(b, 4)
                reps[rep.replica] = r
            return {"replicas": reps, "live": n,
                    "target": self.target_replicas,
                    "leases": self.leases.counts(),
                    "outstanding": len(self.outstanding),
                    "queue_per_replica": round(qpr, 3),
                    "burn": None if burn is None else round(burn, 4),
                    "reprefills": self.reprefills,
                    "ghost_results": self.ghost_results,
                    "scale_ups": self.scale_ups,
                    "scale_downs": self.scale_downs}

    def dump(self, path=None, reason: str = "fleet_episode") -> str:
        """Append the fleet recorder plus EVERY replica's recorder
        (live, dead and retired) into one JSONL —
        ``scripts/slo_report.py --fleet`` replays it."""
        self._record_fleet_snapshot()
        out = self.recorder.dump(path, reason=reason)
        with self._lock:
            reps = [rep for _, rep in sorted(self.replicas.items())]
        for rep in reps:
            rep.scheduler.flight_recorder.dump(out, reason=reason)
        return str(out)
