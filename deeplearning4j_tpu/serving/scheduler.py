"""Continuous-batching inference scheduler over a fixed decode-slot pool.

The μ-cuDNN idea (PAPERS.md, arXiv 1804.04806) applied to serving: keep
the device sweep FULL by slicing admission into fixed-capacity slots
instead of reshaping the batch around each request. One
``GenerationEngine`` cache holds ``n_slots`` sequences; the scheduler
loop interleaves

    admit:  free slot + queued request → jitted per-slot prefill
            (neighbour slots keep decoding state untouched), first
            token sampled from the prefill logits (this is TTFT)
    decode: ONE jitted sweep advances every active slot a token —
            per-slot temperature/top-k vectors let mixed requests share
            the sweep; finished slots free immediately for re-admission

so mixed-length traffic never drains the pool to prefill and a finished
request never strands its neighbours. Each request resolves a
``concurrent.futures.Future`` with a :class:`GenerationResult`.

Preemption (optional, ``starvation_ms``): when the queue head has waited
past the deadline and no slot is free, the active request with the most
REMAINING budget is preempted — its slot frees, its context
(prompt + generated so far) re-queues and is later re-prefilled
(vLLM-style recompute preemption). Greedy decoding is preemption-
transparent: prefill(prompt+generated) reproduces the exact logits the
interrupted decode would have seen (the engine's equivalence guarantee),
so the output is unchanged.

Telemetry rides the unified plane (``dl4j_serving_*`` on the process
registry, spans on the tracer): slot occupancy, queue depth, TTFT /
queue-wait / request-latency histograms, decode-step timing, token and
preemption counters. ``scripts/check_metric_names.py`` lints the sites.

The SLO plane (ISSUE 11) rides on top, host-side only — the device
dispatch sequence is untouched, so greedy scheduler output stays
bit-identical to ``generate()`` with everything below enabled:

- every request carries an ``obs.RequestTrace`` lifecycle timeline
  (submit → queue → admit → prefill → each token → preempt/requeue →
  finish/cancel/fail), stitched into the span tracer on completion and
  feeding the ``dl4j_serving_itl_seconds`` inter-token-latency
  histogram PER REQUEST — a preemption's requeue gap is one (large)
  ITL sample, invisible to per-sweep timing;
- a bounded :class:`~..obs.FlightRecorder` black box keeps the last N
  completed traces + per-step scheduler snapshots (slot map, queue,
  occupancy), dumped as JSONL on demand and automatically when the
  serve loop crashes (``_fail_all``), and served live at
  ``GET /debug/serving`` / ``GET /debug/requests``;
- pass ``slo=SLOConfig(...)`` to account rolling goodput / attainment
  / burn-rate (``dl4j_slo_*`` gauges, ``scheduler.slo.report()``);
- point-in-time gauges carry a ``replica`` label (default ``"0"``) so
  the multi-host router (ROADMAP item 2) reads per-replica load
  unchanged.

The memory & compile plane (ISSUE 12) rides the same host-side-only
contract: a construction-time memory census (params + KV under this
replica's label), per-step KV residency accounting —
``dl4j_kv_allocated_bytes`` vs ``dl4j_kv_resident_bytes`` and the
``dl4j_kv_waste_ratio`` that sizes the paged-KV PR, resident counts
taken from the host-side ``prompt+generated`` mirrors (never a device
fetch) — a per-request ``dl4j_kv_final_residency_ratio`` histogram at
completion, and residency fields on every flight-recorder snapshot so
the black box doubles as the memory timeline (``kv_report()`` /
``GET /debug/memory`` / ``scripts/mem_report.py``). The engine's
jitted entry points sit behind compile sentinels; after
``engine.mark_warm()`` any recompile warns and counts
(``dl4j_compile_retraces_total``).

The trace bookkeeping self-times (``trace_overhead_seconds``, the
MetricsListener precedent); tests pin it under 2% of the decode-sweep
wall clock — with census, sentinel, and residency accounting all on.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import (FlightRecorder, RequestTrace, SLOConfig, SLOTracker,
                   get_registry, span)
from . import kvcache
from .engine import GenerationEngine


@dataclass
class GenerationResult:
    """What a request's future resolves to."""
    tokens: np.ndarray          # generated ids, prompt excluded
    finish_reason: str          # "eos" | "length"
    request_id: int
    ttft_s: Optional[float]     # submit → first token
    latency_s: float            # submit → completion
    preemptions: int


@dataclass
class ServingRequest:
    id: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float
    top_k: int
    eos_id: Optional[int]
    future: Future
    submitted_ts: float
    queued_ts: float            # reset on re-queue after preemption
    first_token_ts: Optional[float] = None
    generated: List[int] = field(default_factory=list)
    preemptions: int = 0
    trace: Optional[RequestTrace] = None
    # chunked-prefill state (ISSUE 14, paged mode): the context being
    # prefilled this admission and how many of its tokens are written;
    # ``pending is None`` means the slot is decoding (or dense mode)
    pending: Optional[np.ndarray] = None
    done_tokens: int = 0
    prefill_s: float = 0.0      # summed chunk wall time, this admission
    chunks: int = 0             # chunks dispatched, this admission
    # prefix sharing (ISSUE 16): the session this request extends (its
    # finish retains pages under the same id), and the tokens the last
    # admission skipped via shared resident pages
    session_id: Optional[str] = None
    prefix_matched: int = 0

    def context(self) -> np.ndarray:
        """Token ids to prefill on (re-)admission: the original prompt
        plus everything generated so far (recompute preemption)."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)


class ContinuousBatchingScheduler:
    """Slot-based admission + full-pool decode over one engine cache.

    Synchronous core: ``step()`` performs one admit+decode iteration and
    is what tests script; ``run_until_idle()`` loops it; ``start()`` /
    ``stop()`` run the same loop on a daemon thread for callers that
    ``submit`` from elsewhere. Metadata (queue/slots) lives under a
    short-held lock so submit never waits on device work; a second lock
    serializes step() iterations (the cache is donated — one dispatch
    at a time). A request whose Future is cancelled while queued is
    dropped before it costs a prefill.
    """

    def __init__(self, engine: GenerationEngine, n_slots: int = 4, *,
                 starvation_ms: Optional[float] = None, key=None,
                 replica: str = "0",
                 slo: Union[SLOConfig, SLOTracker, None] = None,
                 recorder_requests: int = 256,
                 recorder_snapshots: int = 512,
                 crash_dump_path: Optional[str] = None,
                 trace_spans: bool = True,
                 sample_obs_every: int = 32,
                 page_len: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefix_cache: bool = False,
                 quant_kv: Optional[str] = None):
        if n_slots < 1:
            raise ValueError("need at least one decode slot")
        if prefix_cache and page_len is None and n_pages is None:
            raise ValueError("prefix_cache rides the paged pool: give "
                             "page_len and/or n_pages")
        if quant_kv is not None and page_len is None and n_pages is None:
            raise ValueError("quant_kv quantizes the paged pool: give "
                             "page_len and/or n_pages")
        self.engine = engine
        self.n_slots = int(n_slots)
        self.starvation_ms = starvation_ms
        self.replica = str(replica)
        # paged mode (ISSUE 14): give EITHER knob and the pool becomes
        # block-paged — n_pages shared fixed-size pages + a per-slot
        # page table instead of n_slots × max_len dense rows. Admission
        # turns page-availability-based, long prompts prefill in
        # engine.chunk_len chunks interleaved with decode sweeps, and
        # preemption/cancel/finish return pages to the free list.
        # n_pages defaults to full per-slot capacity (no
        # oversubscription); size it DOWN to serve at actual token
        # residency — that is the point (the serving/tune.py sweep and
        # bench rows pick the byte budget).
        self.paged = page_len is not None or n_pages is not None
        # sampler observability (ISSUE 13): every Nth sampling event
        # (decode sweeps and admission first-tokens share one
        # counter), derive next-token entropy + top-k truncated mass
        # host-side from the logits that event produced (0 disables;
        # 1 = every event). Each observation is one (active, V) fetch
        # + a numpy softmax; the default subsamples aggressively
        # because the serving trace budget (<2% of the sweep wall,
        # tests pin it) has little headroom on tiny models — fidelity
        # work that wants every sweep sets 1 explicitly. Counted into
        # trace_overhead_seconds.
        self.sample_obs_every = max(0, int(sample_obs_every))
        self._obs_events = 0
        if self.paged:
            plen = int(page_len if page_len is not None
                       else kvcache.DEFAULT_PAGE_LEN)
            per_slot = -(-engine.max_len // plen)
            np_ = int(n_pages if n_pages is not None
                      else self.n_slots * per_slot)
            # int8 KV storage (ISSUE 19): quant_kv pins the mode
            # (off|on|auto|race); None defers to the engine / env
            # ladder inside serving.quant.decide_kv, whose verdict is
            # the fidelity-gated promotion race. Every path below —
            # CoW splits, prefix sharing, re-prefill, preemption —
            # is mode-blind: scales ride the page axis.
            if quant_kv is not None:
                from . import quant
                qz = quant.decide_kv(engine, self.n_slots, np_, plen,
                                     mode=quant_kv) == "int8"
                self.cache = engine.init_paged_cache(
                    self.n_slots, np_, plen, quantized=qz)
            else:
                self.cache = engine.init_paged_cache(self.n_slots, np_,
                                                     plen)
            self._pages: Optional[kvcache.PageTable] = \
                kvcache.PageTable.for_cache(self.cache)
            self._kv_page_bytes = kvcache.page_nbytes(self.cache)
        else:
            self.cache = engine.init_cache(self.n_slots)
            self._pages = None
            self._kv_page_bytes = 0
        # copy-on-write prefix sharing (ISSUE 16, opt-in): a radix-style
        # index + session retention over the page pool. Admission maps
        # matched prefixes into the new slot's table (zero jitted
        # changes — the gather reads arbitrary page sets) and prefills
        # only the tail; a slot about to scatter into a shared page
        # splits it first via engine.copy_page.
        self._prefix: Optional[kvcache.PrefixCache] = \
            kvcache.PrefixCache(self._pages) if prefix_cache else None
        if self._prefix is not None and hasattr(engine, "copy_page"):
            # warm the CoW page-copy kernel NOW (a src==dst self-copy is
            # a semantic no-op): the first real split may land after
            # mark_warm(), and it must not count as a retrace
            self.cache = engine.copy_page(self.cache, 0, 0)
        # memory plane (ISSUE 12/14): allocated bytes are static under
        # dense slotting (slots × max_len) and MAPPED-page bytes under
        # paging; resident bytes follow the per-slot token counts the
        # scheduler already tracks host-side (prompt + generated — no
        # device fetch on the hot path)
        self._kv_allocated = kvcache.cache_nbytes(self.cache)
        self._kv_token_bytes = kvcache.token_nbytes(self.cache)
        self._kv_last_resident = 0
        self._kv_last_alloc = 0 if self.paged else self._kv_allocated
        self._kv_resident_sum = 0.0
        self._kv_alloc_sum = 0.0
        self._kv_samples = 0
        self._final_res_sum = 0.0
        self._final_res_n = 0
        # peak concurrent active requests over the accounting window —
        # the ≥2×-concurrency-at-equal-bytes evidence the paged bench
        # row reports (ISSUE 14)
        self._peak_active = 0
        self.slots: List[Optional[ServingRequest]] = [None] * self.n_slots
        self._queue: deque = deque()
        self._draining = False      # drain(): admission gate (ISSUE 18)
        # two locks: `_lock` guards the cheap metadata (queue, slots,
        # key, last_tokens) so submit()/inspection never wait on device
        # work; `_step_lock` serializes whole step() iterations — the
        # cache is donated through prefill/decode, so two concurrent
        # steps would hand the same buffer to XLA twice
        self._lock = threading.RLock()
        self._step_lock = threading.Lock()
        self._key = jax.random.PRNGKey(0) if key is None else key
        self._last_tokens = np.zeros((self.n_slots,), np.int32)
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # SLO plane (ISSUE 11): black box + per-request traces + SLO
        self.flight_recorder = FlightRecorder(
            capacity_requests=recorder_requests,
            capacity_snapshots=recorder_snapshots, replica=self.replica,
            crash_dump_path=crash_dump_path)
        self.flight_recorder.extra_state = self._debug_extra
        if isinstance(slo, SLOTracker):
            self.slo: Optional[SLOTracker] = slo
        elif slo is not None:
            self.slo = SLOTracker(slo, replica=self.replica)
        else:
            self.slo = None
        self.trace_spans = trace_spans
        self._steps = 0
        self._trace_overhead = 0.0
        # publish the pool's memory census once (construction, not hot
        # path): params + KV attribution under this replica's label,
        # and the static allocated-bytes gauge. Decoration only — a
        # census failure (e.g. a user metric squatting on the name with
        # other labels) must not take down serving.
        try:
            from ..obs import memory as obs_memory
            obs_memory.emit_census(
                {"params": engine.params, "kv_cache": self.cache},
                replica=self.replica, source="serving")
            m = self._m()
            m["kv_alloc"].set(float(self._kv_last_alloc),
                              replica=self.replica)
        except Exception:  # noqa: BLE001 — census is decoration
            pass

    # ------------------------------------------------------- metrics
    @staticmethod
    def _m():
        reg = get_registry()
        return {
            "requests": reg.counter(
                "dl4j_serving_requests_total",
                "Requests submitted to the continuous-batching scheduler"),
            "completions": reg.counter(
                "dl4j_serving_completions_total",
                "Requests completed, by finish reason",
                labelnames=("reason",)),
            "preemptions": reg.counter(
                "dl4j_serving_preemptions_total",
                "Active requests preempted (recompute on re-admission)"),
            "prefills": reg.counter(
                "dl4j_serving_prefills_total",
                "Per-slot prefill admissions (includes re-admissions)"),
            "decode_steps": reg.counter(
                "dl4j_serving_decode_steps_total",
                "Full-pool decode sweeps executed"),
            "tokens": reg.counter(
                "dl4j_serving_tokens_total",
                "Tokens generated across all requests"),
            "occupancy": reg.gauge(
                "dl4j_serving_slot_occupancy",
                "Active slots / pool size at the last decode sweep "
                "(0 when the pool is idle)",
                labelnames=("replica",)),
            "queue_depth": reg.gauge(
                "dl4j_serving_queue_depth",
                "Requests waiting for a decode slot",
                labelnames=("replica",)),
            "tokens_per_s": reg.gauge(
                "dl4j_serving_tokens_per_second",
                "Generated tokens per second over the last decode sweep "
                "(0 when the pool is idle)",
                labelnames=("replica",)),
            "ttft": reg.histogram(
                "dl4j_serving_ttft_seconds",
                "Time from submit to first generated token"),
            "queue_wait": reg.histogram(
                "dl4j_serving_queue_wait_seconds",
                "Time a request waited in the admission queue"),
            "decode_s": reg.histogram(
                "dl4j_serving_decode_step_seconds",
                "Wall time of one full-pool decode sweep"),
            "itl": reg.histogram(
                "dl4j_serving_itl_seconds",
                "Inter-token latency, derived per request from its "
                "lifecycle trace (a preemption requeue gap is one "
                "sample)"),
            "latency": reg.histogram(
                "dl4j_serving_request_latency_seconds",
                "Time from submit to request completion"),
            # KV residency accounting (ISSUE 12/14): allocated vs
            # resident bytes — dense slots allocate max_len per slot,
            # the paged pool allocates only MAPPED pages
            "kv_alloc": reg.gauge(
                "dl4j_kv_allocated_bytes",
                "Allocated KV bytes: slots x max_len (dense slotting) "
                "or mapped pages x page bytes (paged pool)",
                labelnames=("replica",)),
            "kv_res": reg.gauge(
                "dl4j_kv_resident_bytes",
                "KV bytes actually holding tokens (active slots' "
                "prompt+generated counts x per-token bytes)",
                labelnames=("replica",)),
            "kv_waste": reg.gauge(
                "dl4j_kv_waste_ratio",
                "1 - resident/allocated (dense idle pool = 1.0; paged "
                "counts mapped pages, so waste is only unfilled page "
                "tails)", labelnames=("replica",)),
            # CoW prefix sharing census (ISSUE 16) — shared pages count
            # ONCE in kv_alloc above; these expose the sharing itself
            "kv_shared": reg.gauge(
                "dl4j_kv_shared_pages",
                "Pool pages with more than one holder (slot mappings + "
                "prefix-cache/session holds) at the last snapshot",
                labelnames=("replica",)),
            "kv_cached": reg.gauge(
                "dl4j_kv_cached_pages",
                "Pool pages resident only because the prefix cache "
                "holds them — the LRU-evictable reclaim headroom",
                labelnames=("replica",)),
            "kv_cow": reg.counter(
                "dl4j_kv_cow_copies_total",
                "Copy-on-write page splits (device page copies) before "
                "a slot scattered into a shared page"),
            "kv_prefix_hits": reg.counter(
                "dl4j_kv_prefix_hits_total",
                "Admissions that mapped a shared resident prefix "
                "instead of re-prefilling it"),
            "kv_prefix_hit_tokens": reg.counter(
                "dl4j_kv_prefix_hit_tokens_total",
                "Prompt tokens skipped at prefill because their pages "
                "were already resident (prefix/session hits)"),
            "kv_prefix_evictions": reg.counter(
                "dl4j_kv_prefix_evictions_total",
                "Cached prefix pages freed by LRU eviction under page "
                "pressure (before the preemption path)"),
            "kv_final": reg.histogram(
                "dl4j_kv_final_residency_ratio",
                "Per-request final residency at completion: "
                "(prompt+generated) / max_len under dense slotting, "
                "/ mapped-page capacity under paging — how much of "
                "what it reserved a request ever used",
                buckets=tuple(i / 20 for i in range(1, 21))),
            # sampler observability (ISSUE 13): health of the model's
            # next-token distribution at the sampling sites — a
            # quantized KV cache or int8 weights (ROADMAP 3) that
            # flattens or spikes it shows up here first
            "sample_entropy": reg.histogram(
                "dl4j_serving_sample_entropy",
                "Per-observation mean entropy (nats) of the MODEL's "
                "next-token distribution (softmax at temperature 1, "
                "before per-request temperature/top-k shaping) over "
                "active slots — the sharpness signal quantization "
                "drift shows up in, meaningful for greedy pools too",
                buckets=tuple(0.25 * i for i in range(1, 61))),
            "topk_mass": reg.histogram(
                "dl4j_serving_topk_mass",
                "Per-observation mean probability mass (at temperature "
                "1) the top-k truncation keeps, over active slots with "
                "top_k > 0",
                buckets=tuple(i / 20 for i in range(1, 21))),
        }

    # -------------------------------------------------------- submit
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None,
               session_id: Optional[str] = None) -> Future:
        """Queue a generation request; returns a Future resolving to a
        :class:`GenerationResult`. Rejects requests that could never fit
        a slot (prompt + budget beyond the cache's ``max_len``) up
        front — admission never has to partially honour a request.

        ``session_id`` (ISSUE 16, needs ``prefix_cache=True``) threads a
        multi-turn conversation: at finish the request's written pages
        are RETAINED under the id, and the next ``submit`` whose prompt
        extends the retained context maps those pages instead of
        re-prefilling the history — the new turn's delta becomes
        append-only. Each turn's retention supersedes the last;
        :meth:`drop_session` releases it explicitly."""
        if session_id is not None and self._prefix is None:
            raise ValueError("session_id needs prefix_cache=True (and "
                             "the paged pool)")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + max_new_tokens - 1
        if total > self.engine.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) - 1 = {total} exceeds the slot "
                f"capacity max_len={self.engine.max_len}")
        if self.paged and self._pages.pages_for(total) > self._pages.n_pages:
            raise ValueError(
                f"request needs {self._pages.pages_for(total)} pages "
                f"({total} tokens at page_len={self._pages.page_len}) "
                f"but the pool holds {self._pages.n_pages} — it could "
                "never run even alone")
        now = time.perf_counter()
        fut: Future = Future()
        with self._lock:
            if self._draining:
                raise RuntimeError("scheduler is draining — submit to "
                                   "another replica")
            req = ServingRequest(
                id=self._next_id, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), top_k=int(top_k),
                eos_id=eos_id, future=fut, submitted_ts=now,
                queued_ts=now, session_id=session_id)
            req.trace = RequestTrace(request_id=req.id,
                                     replica=self.replica)
            req.trace.event("submit", ts=now,
                            prompt_tokens=int(prompt.size),
                            max_new_tokens=int(max_new_tokens))
            req.trace.event("queue", ts=now)
            self._next_id += 1
            self._queue.append(req)
            m = self._m()
            m["requests"].inc()
            m["queue_depth"].set(len(self._queue), replica=self.replica)
        return fut

    # ---------------------------------------------------------- step
    def step(self) -> bool:
        """One scheduler iteration: preempt-if-starved, admit, decode.
        Returns True if any work happened (False = fully idle).

        Device work (prefill, the decode sweep, any compile it
        triggers) runs OUTSIDE the metadata lock — a client thread's
        submit() never waits on a sweep — while ``_step_lock``
        serializes iterations so the donated cache is never dispatched
        twice."""
        with self._step_lock:
            m = self._m()
            with self._lock:
                did = self._maybe_preempt(m)
                admissions = self._pop_admissions(m)
            if self.paged:
                # chunked prefill (ISSUE 14): every prefilling slot —
                # just admitted or mid-prompt — advances ONE chunk,
                # then the decode sweep runs; a T=4096 admission costs
                # each sweep a chunk-sized pause, never the whole
                # prompt
                did = self._advance_prefills(m) or did
            else:
                for slot, req in admissions:
                    self._admit_one(slot, req, m)
            did = did or bool(admissions)
            did = self._decode_sweep(m) or did
            with self._lock:
                m["queue_depth"].set(len(self._queue),
                                     replica=self.replica)
            if did:
                t_ov = time.perf_counter()
                self._record_snapshot(m)
                self._trace_overhead += time.perf_counter() - t_ov
            else:
                # idle reset: the occupancy/throughput gauges used to
                # freeze at their last busy value after the pool
                # drained — a router reading them would keep routing
                # around a replica that is actually free. Residency
                # drains with it: an idle fixed pool is 100% waste.
                m["occupancy"].set(0.0, replica=self.replica)
                m["tokens_per_s"].set(0.0, replica=self.replica)
                # dense idle = 100% waste (max_len × slots preallocated
                # for nothing); paged idle maps NOTHING — zero
                # allocated, zero wasted, which is the whole point.
                # With the prefix cache, idle residency is whatever the
                # cache still HOLDS (ISSUE 16): cached pages occupy
                # real pool bytes until evicted, and the gauges must
                # say so.
                if self.paged and self._prefix is not None:
                    with self._lock:
                        alloc = self._pages.used_pages \
                            * self._kv_page_bytes
                        resident = min(
                            alloc, self._pages.resident_tokens
                            * self._kv_token_bytes)
                        self._kv_last_resident = resident
                        self._kv_last_alloc = alloc
                    m["kv_alloc"].set(float(alloc), replica=self.replica)
                    m["kv_res"].set(float(resident),
                                    replica=self.replica)
                    m["kv_waste"].set(
                        (1.0 - resident / alloc) if alloc else 0.0,
                        replica=self.replica)
                    m["kv_cached"].set(float(self._prefix.cached_pages),
                                       replica=self.replica)
                    m["kv_shared"].set(float(self._pages.shared_pages),
                                       replica=self.replica)
                else:
                    m["kv_res"].set(0.0, replica=self.replica)
                    if self.paged:
                        m["kv_alloc"].set(0.0, replica=self.replica)
                        m["kv_waste"].set(0.0, replica=self.replica)
                    else:
                        m["kv_waste"].set(1.0, replica=self.replica)
                    with self._lock:   # writers-hold-_lock invariant
                        self._kv_last_resident = 0
                        if self.paged:
                            self._kv_last_alloc = 0
        return did

    def run_until_idle(self, max_steps: int = 100000):
        """Drive step() until queue and pool are empty (tests, batch
        jobs). ``max_steps`` is a runaway guard, generous vs any real
        trace (one step ≥ one token for every active slot)."""
        for _ in range(max_steps):
            with self._lock:
                idle = not self._queue and not any(self.slots)
            if idle:
                return
            self.step()
        raise RuntimeError(f"scheduler not idle after {max_steps} steps")

    # ---------------------------------------------------- background
    def start(self, poll_s: float = 0.001):
        """Serve from a daemon thread until stop(): step() when there is
        work, sleep ``poll_s`` when idle. The thread is stopped at
        interpreter exit if still running — a daemon thread caught
        mid-decode while jax tears down aborts the process."""
        if self._thread is not None:
            return self
        if not getattr(self, "_atexit_registered", False):
            import atexit
            import weakref
            ref = weakref.ref(self)
            atexit.register(lambda: (lambda s: s and s.stop())(ref()))
            self._atexit_registered = True
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.is_set():
                try:
                    worked = self.step()
                except Exception as e:  # noqa: BLE001 — a dying serve
                    # thread must FAIL the in-flight futures, not strand
                    # their callers on result() forever
                    self._fail_all(e)
                    raise
                if not worked:
                    self._stop_evt.wait(poll_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dl4j-serving-scheduler")
        self._thread.start()
        return self

    def _fail_all(self, exc: BaseException):
        """Resolve every queued and in-flight future with ``exc``, clear
        the pool, and leave a black box: a crash snapshot of the dying
        slot map + every doomed request's trace, dumped as JSONL (the
        serve-loop crash path). The futures fail FIRST — callers
        blocked in result() must not wait out the recording pass — and
        none of the recording may mask ``exc``."""
        with self._lock:
            slot_ids = [None if r is None else r.id for r in self.slots]
            queued_ids = [r.id for r in self._queue]
            doomed = [r for r in self.slots if r is not None] + \
                list(self._queue)
            self.slots = [None] * self.n_slots
            self._queue.clear()
            if self.paged:      # dead pool leaks no pages
                self._pages.reset()
                if self._prefix is not None:
                    # reset() zeroed the refcounts the cache's holds
                    # backed — drop the bookkeeping without decref
                    self._prefix.forget()
        for req in doomed:
            try:
                req.future.set_exception(exc)
            except InvalidStateError:
                pass
        err = repr(exc)[:300]
        try:
            m = self._m()
            self._steps += 1
            self.flight_recorder.record_snapshot(
                step=self._steps, crash=True, error=err, slots=slot_ids,
                queue=queued_ids, queue_depth=len(queued_ids),
                occupancy=sum(s is not None for s in slot_ids)
                / self.n_slots)
            for req in doomed:
                self._close_trace(req, "fail", m, error=err)
            self.flight_recorder.dump(reason="fail_all")
        except Exception:  # noqa: BLE001 — a failed postmortem (full
            pass           # disk, torn state) must not mask exc

    def stop(self):
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=30)
        self._thread = None

    def drain(self, max_steps: int = 100000) -> List["ServingRequest"]:
        """Graceful retire (ISSUE 18): stop admission, FINISH every
        request already occupying a slot (their futures resolve
        normally), then hand back the still-unstarted queue entries
        instead of failing them — the fleet router re-routes those to a
        surviving replica. Contrast ``_fail_all``, the crash path.

        Returned entries may include recompute-preemption victims whose
        futures are already RUNNING and whose ``generated`` is partial;
        re-running the ORIGINAL prompt elsewhere reproduces the same
        greedy output (prefill recomputes exactly the logits the
        interrupted decode would have seen), so the router resubmits
        ``req.prompt`` and resolves the caller from the fresh run.

        Safe to call while the background serve loop runs — the flag
        stops its admissions too and ``step()`` is ``_step_lock``-
        serialized; the scheduler accepts submits again after drain
        returns (the router usually discards it instead)."""
        with self._lock:
            self._draining = True
        try:
            for _ in range(max_steps):
                with self._lock:
                    busy = any(self.slots)
                if not busy:
                    break
                self.step()
            else:
                raise RuntimeError(
                    f"drain: pool not empty after {max_steps} steps")
            with self._lock:
                leftover = list(self._queue)
                self._queue.clear()
                self._m()["queue_depth"].set(0, replica=self.replica)
            return leftover
        finally:
            with self._lock:
                self._draining = False

    # ------------------------------------------------------ internals
    def _free_slots(self):
        return [i for i, r in enumerate(self.slots) if r is None]

    def _admission_plan(self, req):
        """Paged-admission plan for ``req`` (caller holds ``_lock``):
        ``(shared_pages, matched_tokens, need)`` — the resident pages
        its prompt prefix already has (ISSUE 16: session retention
        first, then the block index), the prompt tokens those cover,
        and the FREE pages its first prefill chunk still needs. The
        match is capped at ``ctx_len - 1`` so at least one token always
        prefills — the final chunk's logits are the first-token sample.
        Without the prefix cache this degenerates to the PR 14
        first-chunk page count."""
        ctx_len = req.prompt.size + len(req.generated)
        if self._prefix is None:
            return [], 0, self._pages.pages_for(
                min(ctx_len, self.engine.chunk_len))
        ctx = req.context()
        cap = ctx_len - 1
        shared: List[int] = []
        matched = 0
        if req.session_id is not None:
            sm = self._prefix.session_match(req.session_id, ctx)
            if sm is not None:
                n, shared = sm
                # identical resubmit: keep the pages (CoW rewrites the
                # tail position) but leave one token to prefill
                matched = min(n, cap)
        if not shared:
            shared = self._prefix.match(ctx)
            while shared and len(shared) * self._pages.page_len > cap:
                shared.pop()
            matched = len(shared) * self._pages.page_len
        first_end = min(ctx_len, matched + self.engine.chunk_len)
        need = max(0, self._pages.pages_for(first_end) - len(shared))
        return shared, matched, need

    def _head_first_chunk_pages(self) -> int:
        """FREE pages the queue head's first prefill chunk needs, net
        of any resident shared prefix (paged)."""
        return self._admission_plan(self._queue[0])[2]

    def _preempt_slot(self, victim_slot: int, m) -> "ServingRequest":
        """Preempt the request in ``victim_slot`` (caller holds
        ``_lock``): free the lane, return its pages to the pool, reset
        any mid-prefill progress, and re-queue its context at the BACK
        (recompute preemption). Shared by the starvation guard and the
        page-pressure path."""
        victim = self.slots[victim_slot]
        self.slots[victim_slot] = None
        self._release_pages(victim_slot)
        victim.pending = None
        victim.done_tokens = 0
        victim.preemptions += 1
        victim.queued_ts = time.perf_counter()
        if victim.trace is not None:
            victim.trace.event("preempt", ts=victim.queued_ts,
                               slot=victim_slot,
                               generated=len(victim.generated))
            victim.trace.event("requeue", ts=victim.queued_ts)
        self._queue.append(victim)
        m["preemptions"].inc()
        return victim

    def _release_pages(self, slot: int) -> int:
        """Paged mode: drop the slot's page holds (a no-op under dense
        slotting). Returns mappings removed; pages the prefix cache
        still holds stay resident (cached) rather than freeing."""
        return self._pages.release(slot) if self.paged else 0

    def _slot_pages(self, slot: int) -> List[int]:
        """The slot's mapped pool pages in logical order (paged mode,
        caller holds ``_lock``)."""
        return [int(self._pages.table[slot, j])
                for j in range(int(self._pages.mapped[slot]))]

    def _retire_slot(self, slot: int, req: "ServingRequest") -> int:
        """Finish-path page retirement (caller holds ``_lock``): with
        the prefix cache, REGISTER the request's written context before
        dropping the slot's holds — full blocks into the block index
        (cross-request sharing), and, for a ``session_id`` request, the
        whole written mapping (partial tail page included) under the
        session so the next turn resumes append-only. The last sampled
        token's k/v was never written, so the retained context stops
        one short. Preemption does NOT register (its whole point is to
        actually free pages — registration there would livelock the
        page-pressure path). Returns mappings removed."""
        if not self.paged:
            return 0
        if self._prefix is not None:
            ctx = req.context()
            written = int(ctx.size) - 1
            pages = self._slot_pages(slot)
            if written > 0 and pages:
                self._pages.note_fill(slot, written)
                self._prefix.insert(ctx[:written], pages)
                if req.session_id is not None:
                    keep = self._pages.pages_for(written)
                    self._prefix.retain_session(
                        req.session_id, ctx[:written], pages[:keep])
        return self._pages.release(slot)

    def _maybe_preempt(self, m) -> bool:
        """Starvation guard: queue head waited past the deadline and
        cannot admit — no free slot, or (paged) not enough free pages
        for its first chunk → preempt the active request with the most
        remaining budget (it blocks the pool longest). Its context
        re-queues at the BACK; the head admits into the freed
        lane/pages this same step."""
        if self.starvation_ms is None or not self._queue or self._draining:
            return False
        if self._free_slots() and not (
                self.paged
                and self._head_first_chunk_pages() > self._pages.free_pages):
            return False
        waited_ms = (time.perf_counter() - self._queue[0].queued_ts) * 1e3
        if waited_ms <= self.starvation_ms:
            return False
        # victims come from the DECODING slots only: a mid-chunked-
        # prefill slot always carries the pool's max remaining budget
        # (nothing generated yet), so including it would win every
        # max() and then fail the nothing-to-save guard — silently
        # disabling starvation relief for the whole multi-step
        # admission window chunked prefill creates
        victim_slot = max(
            (i for i, r in enumerate(self.slots)
             if r is not None and r.pending is None),
            key=lambda i: self.slots[i].remaining(), default=None)
        if victim_slot is None:
            return False
        victim = self.slots[victim_slot]
        if victim.remaining() <= 0 or not victim.generated:
            return False       # nothing to save / about to finish anyway
        self._preempt_slot(victim_slot, m)
        return True

    def _pop_admissions(self, m):
        """Under the metadata lock: pair free slots with queued requests
        and RESERVE the slots (so occupancy readers see them) before the
        device-side prefills run lock-free. A request whose future was
        cancelled while queued is dropped here — it never costs a
        prefill. Paged mode gates admission on PAGE availability too
        (the head's first chunk must fit the free list) — the pool
        admits to actual token residency, not lane count."""
        out = []
        if self._draining:      # drain(): queued entries stay queued —
            return out          # they are handed back, not admitted
        reserved = 0            # pages promised to this batch's heads
        for slot in self._free_slots():
            admitted = False
            while self._queue:
                req = self._queue[0]
                if self.paged:
                    shared, matched, need = self._admission_plan(req)
                    if need > self._pages.free_pages - reserved:
                        # LRU-evict cold cached prefix pages BEFORE
                        # refusing admission (ISSUE 16) — the pages the
                        # head just matched are protected until mapped
                        if self._prefix is not None:
                            freed = self._prefix.evict(
                                need - (self._pages.free_pages
                                        - reserved),
                                protect=frozenset(shared))
                            if freed:
                                m["kv_prefix_evictions"].inc(freed)
                        if need > self._pages.free_pages - reserved:
                            break   # FIFO holds: nothing admits past a
                                    # head that cannot get pages
                self._queue.popleft()
                # fresh requests are PENDING → claim them (rejecting
                # cancelled ones); a re-queued preemption victim is
                # already RUNNING and must not be re-claimed
                if not req.future.running() and \
                        not req.future.set_running_or_notify_cancel():
                    m["completions"].inc(reason="cancelled")
                    self._close_trace(req, "cancel", m)
                    continue
                now = time.perf_counter()
                m["queue_wait"].observe(now - req.queued_ts)
                if req.trace is not None:
                    req.trace.event("admit", ts=now, slot=slot)
                if self.paged:
                    req.pending = req.context()
                    req.done_tokens = 0
                    req.prefill_s = 0.0
                    req.chunks = 0
                    req.prefix_matched = 0
                    if shared:
                        # map the matched prefix NOW (same lock hold as
                        # the plan — eviction cannot slip between):
                        # those tokens never prefill, the tail chunks
                        # start past them
                        self._pages.map_shared(slot, shared)
                        self._pages.note_fill(slot, matched)
                        req.done_tokens = matched
                        req.prefix_matched = matched
                        self._prefix.note_hit(matched)
                        m["kv_prefix_hits"].inc()
                        m["kv_prefix_hit_tokens"].inc(matched)
                        if req.trace is not None:
                            req.trace.event(
                                "prefix_hit", ts=now,
                                matched_tokens=int(matched),
                                shared_pages=len(shared))
                    reserved += need
                self.slots[slot] = req        # reserve
                out.append((slot, req))
                admitted = True
                break
            if not admitted:
                break
        return out

    def _admit_one(self, slot, req, m):
        """Device-side admission for one reserved slot (dense mode):
        prefill the request's whole context, sample its first token
        (TTFT). Runs outside the metadata lock — `_step_lock` already
        serializes cache use."""
        ctx = req.context()
        t0 = time.perf_counter()
        with span("serving.prefill",
                  attrs={"request": req.id, "slot": slot,
                         "tokens": int(ctx.size)}):
            logits, self.cache = self.engine.prefill_slot(
                self.cache, ctx, slot)
        self._first_token(slot, req, logits, int(ctx.size),
                          time.perf_counter() - t0, m)

    def _advance_prefills(self, m) -> bool:
        """Paged mode: advance every prefilling slot by ONE chunk (the
        ISSUE 14 interleave — the decode sweep that follows never waits
        out more than ``engine.chunk_len`` prompt tokens). Pages for
        the chunk are mapped first; under page pressure the biggest-
        remaining active neighbour is preempted, and if the pool STILL
        cannot cover the chunk the prefilling request itself re-queues
        (its turn comes back when pages free). The final chunk's logits
        are the request's first token (TTFT)."""
        with self._lock:
            work = [(i, r) for i, r in enumerate(self.slots)
                    if r is not None and r.pending is not None]
        did = False
        for slot, req in work:
            with self._lock:
                if self.slots[slot] is not req:   # preempted meanwhile
                    continue
                ctx = req.pending
                done = req.done_tokens
                n = min(self.engine.chunk_len, len(ctx) - done)
                ok = self._ensure_pages(slot, req, done + n, m)
                # CoW (ISSUE 16): pages this chunk writes into that
                # have other holders split first — planned under the
                # lock, copied on device outside it
                cows = self._plan_cow(slot, done, done + n, m) \
                    if ok and self.slots[slot] is req else []
            if not ok:
                did = True      # a preemption shuffle IS work
                continue
            did = True
            for src, dst in cows:
                self.cache = self.engine.copy_page(self.cache, src, dst)
            self.cache = self._pages.sync(self.cache)
            t0 = time.perf_counter()
            with span("serving.prefill_chunk",
                      attrs={"request": req.id, "slot": slot,
                             "start": int(done), "tokens": int(n)}):
                logits, self.cache = self.engine.prefill_chunk(
                    self.cache, ctx[done:done + n], slot, start=done)
            with self._lock:
                req.prefill_s += time.perf_counter() - t0
                req.chunks += 1
                req.done_tokens = done + n
                final = req.done_tokens >= len(ctx)
                if final:
                    req.pending = None
            if final:
                self._first_token(slot, req, logits, len(ctx),
                                  req.prefill_s, m, chunks=req.chunks)
        return did

    def _ensure_pages(self, slot, req, tokens: int, m) -> bool:
        """Grow ``slot``'s mapping to cover ``tokens`` rows, preempting
        under page pressure (caller holds ``_lock``). Victim order:
        DECODING slots first, by most remaining budget — they block the
        pool longest and a recompute costs them one prefill; a
        mid-chunked-prefill slot is only sacrificed when no decoding
        victim frees enough, least-progress first — discarding a
        nearly-done long prefill for one page of decode growth would
        re-pay every chunk AND invite the same squeeze on re-admission
        (livelock by thrash). If the pool still cannot cover the
        growth, ``req`` itself is preempted (False: the lane is free,
        the request re-queued — never stranded, the submit-time fit
        check guarantees it runs once pages free up).

        With the prefix cache (ISSUE 16), LRU eviction of cold cached
        pages runs BEFORE the preemption cascade and again after each
        preemption (a victim's release may leave its registered pages
        cached rather than free)."""
        if self._try_map(slot, tokens, m):
            return True
        while True:
            victim_slot = max(
                (i for i, r in enumerate(self.slots)
                 if r is not None and i != slot),
                key=lambda i: (self.slots[i].pending is None,
                               -self.slots[i].done_tokens
                               if self.slots[i].pending is not None
                               else self.slots[i].remaining()),
                default=None)
            if victim_slot is None:
                break
            self._preempt_slot(victim_slot, m)
            if self._try_map(slot, tokens, m):
                return True
        self._preempt_slot(slot, m)
        return False

    def _try_map(self, slot, req_or_slot_tokens, m=None) -> bool:
        """``PageTable.map`` with the ISSUE 16 eviction step: when the
        free list cannot cover the growth, LRU-evict cached prefix
        pages (cold cache beats preempting live requests) and retry
        once. Caller holds ``_lock``."""
        tokens = int(req_or_slot_tokens)
        if self._pages.map(slot, tokens):
            return True
        if self._prefix is not None:
            short = (self._pages.pages_for(tokens)
                     - int(self._pages.mapped[slot])
                     - self._pages.free_pages)
            if short > 0:
                freed = self._prefix.evict(short)
                if freed and m is not None:
                    m["kv_prefix_evictions"].inc(freed)
                if freed and self._pages.map(slot, tokens):
                    return True
        return False

    def _plan_cow(self, slot, start: int, end: int, m) -> list:
        """Split every page ``slot`` is about to write (context rows
        ``[start, end)``) that has other holders (ISSUE 16 CoW). Caller
        holds ``_lock``; returns the ``(src, dst)`` pool-page copies
        the caller must run on device (``engine.copy_page``) BEFORE the
        write dispatch — device work never runs under the lock.

        Starvation ladder when no free page exists for the split:
        evict cold cache, then transfer sole ownership (drop the cache
        holds on the contested page — the write is then private, no
        copy needed), then preempt the other slot mapping it."""
        if self._prefix is None or end <= start:
            return []
        plen = self._pages.page_len
        copies = []
        for j in range(start // plen, (end - 1) // plen + 1):
            if j >= int(self._pages.mapped[slot]):
                break
            while True:
                p = int(self._pages.table[slot, j])
                if int(self._pages.refcount[p]) <= 1:
                    break                      # private: write in place
                split = self._pages.cow(slot, j)
                if split is not None:
                    copies.append(split)
                    self._prefix.cow_copies += 1
                    m["kv_cow"].inc()
                    break
                # no free page for the copy: reclaim, cheapest first
                freed = self._prefix.evict(1)
                if freed:
                    m["kv_prefix_evictions"].inc(freed)
                    continue
                if self._prefix.release_page_holds(p):
                    continue                   # may now be private
                other = next(
                    (i for i in range(self.n_slots)
                     if i != slot and self.slots[i] is not None
                     and p in self._pages.table[
                         i, :int(self._pages.mapped[i])]),
                    None)
                if other is None:              # cannot happen: refs
                    break                      # must come from somewhere
                self._preempt_slot(other, m)
        return copies

    def _first_token(self, slot, req, logits, ctx_tokens: int,
                     prefill_s: float, m, chunks: Optional[int] = None):
        """Shared admission tail (dense prefill_slot and the final
        prefill chunk): sample the first token — the TTFT sample —
        record the trace events, and either park the token for the next
        sweep or finish immediately (budget 1 / instant eos)."""
        m["prefills"].inc()
        with self._lock:
            self._key, sub = jax.random.split(self._key)
        tok = int(np.asarray(self.engine.sample(
            sub, logits[None], req.temperature, req.top_k))[0])
        # the TTFT timestamp is taken BEFORE the sampler-obs pass: its
        # cost is booked to trace_overhead, so it must not also ride
        # the recorded first-token latency (no double counting)
        now = time.perf_counter()
        # sampler obs (ISSUE 13) on the first (TTFT) token
        obs_cost = self._maybe_sample_obs(m, lambda: np.asarray(logits),
                                          [req.top_k])
        with self._lock:
            self._trace_overhead += obs_cost
            if req.first_token_ts is None:
                req.first_token_ts = now
                m["ttft"].observe(now - req.submitted_ts)
            if req.trace is not None:
                t_ov = time.perf_counter()
                attrs = {} if chunks is None else {"chunks": chunks}
                req.trace.event("prefill", ts=now, slot=slot,
                                tokens=ctx_tokens, time_s=prefill_s,
                                **attrs)
                req.trace.event("token", ts=now, i=len(req.generated))
                self._trace_overhead += time.perf_counter() - t_ov
            if self.paged and self._prefix is not None:
                # register the just-prefilled context's full blocks so
                # CONCURRENT requests with the same prompt share them
                # from their own admission onward (finish re-registers
                # the generated extension)
                ctx_now = req.context()
                self._pages.note_fill(slot, ctx_now.size)
                self._prefix.insert(
                    ctx_now, self._slot_pages(slot))
            req.generated.append(tok)
            m["tokens"].inc()
            if self._done(req, tok):
                self.slots[slot] = None
                released = self._retire_slot(slot, req)
                self._finish(req, tok, m, mapped_pages=released)
            else:
                self._last_tokens[slot] = tok

    def _maybe_sample_obs(self, m, rows_fn, topks) -> float:
        """Shared sampler-obs cadence for admissions and sweeps (one
        counter, one modulo, one timing discipline): returns the
        self-timed cost to add to trace_overhead. ``rows_fn`` defers
        the logits fetch until the cadence says observe — runs under
        ``_step_lock`` only, like its two callers."""
        if not self.sample_obs_every:
            return 0.0
        self._obs_events += 1
        if self._obs_events % self.sample_obs_every:
            return 0.0
        t_obs = time.perf_counter()
        try:
            self._sample_obs(m, rows_fn(), topks)
        except Exception:  # noqa: BLE001 — observability must never
            pass           # perturb the admission or sweep
        return time.perf_counter() - t_obs

    @staticmethod
    def _sample_obs(m, logits_rows, topks):
        """Sampler observability (ISSUE 13), host-side only: mean
        next-token entropy over the given logit rows, and the mean
        probability mass the top-k filter keeps for rows with
        top_k > 0. No device computation — one fetch of logits the
        sampler produced anyway; f32 + in-place numpy + partition
        (not sort) keep an observation in the tens of microseconds."""
        lg = np.array(logits_rows, np.float32, copy=True)
        if lg.ndim == 1:
            lg = lg[None, :]
        if lg.size == 0:
            return
        lg -= lg.max(axis=-1, keepdims=True)
        np.exp(lg, out=lg)
        lg /= lg.sum(axis=-1, keepdims=True)        # lg is now p
        ent = -(lg * np.log(lg + 1e-30)).sum(axis=-1)
        m["sample_entropy"].observe(float(ent.mean()))
        mass, n_k = 0.0, 0
        for row, k in zip(lg, topks):
            k = int(k)
            if k <= 0:
                continue
            k = min(k, row.size)
            mass += float(np.partition(row, row.size - k)
                          [row.size - k:].sum())
            n_k += 1
        if n_k:
            m["topk_mass"].observe(mass / n_k)

    def _decode_sweep(self, m) -> bool:
        with self._lock:      # snapshot; only step() (serialized) mutates
            if self.paged:
                # page growth BEFORE the sweep: each decoding slot's
                # next write position must be mapped (a data update,
                # never a retrace — the gather shape is fixed). Under
                # pressure _ensure_pages preempts, so re-derive the
                # active set afterwards.
                cows = []
                for i in range(self.n_slots):
                    req = self.slots[i]
                    if req is None or req.pending is not None:
                        continue
                    w = req.prompt.size + len(req.generated)
                    ok = self._ensure_pages(i, req, w, m)
                    if ok and self.slots[i] is req:
                        # the sweep writes this slot's row w-1: split
                        # it first if shared (ISSUE 16 — e.g. a session
                        # append into the retained partial tail page)
                        cows.extend(self._plan_cow(i, w - 1, w, m))
            else:
                cows = []
            active = [i for i, r in enumerate(self.slots)
                      if r is not None and r.pending is None]
            if not active:
                return False
            temps = np.zeros((self.n_slots,), np.float32)
            topks = np.zeros((self.n_slots,), np.int32)
            for i in active:
                temps[i] = self.slots[i].temperature
                topks[i] = self.slots[i].top_k
            tokens_in = jnp.asarray(self._last_tokens)
            self._key, sub = jax.random.split(self._key)
        if self.paged:
            for src, dst in cows:
                self.cache = self.engine.copy_page(self.cache, src, dst)
            self.cache = self._pages.sync(self.cache)
        t0 = time.perf_counter()
        with span("serving.decode", attrs={"active": len(active)}):
            logits, self.cache = self.engine.decode_step(
                self.cache, tokens_in)
            toks = np.asarray(self.engine.sample(sub, logits, temps, topks))
        dt = time.perf_counter() - t0
        m["decode_steps"].inc()
        m["decode_s"].observe(dt)
        m["occupancy"].set(len(active) / self.n_slots,
                           replica=self.replica)
        m["tokens"].inc(len(active))
        if dt > 0:
            m["tokens_per_s"].set(len(active) / dt, replica=self.replica)
        # token timestamp BEFORE the sampler-obs pass: its cost is
        # booked to trace_overhead, so it must not also skew the ITL
        # samples derived from consecutive token events (the same
        # no-double-counting discipline as _admit's TTFT timestamp)
        tok_ts = time.perf_counter()
        obs_cost = self._maybe_sample_obs(
            m, lambda: np.asarray(logits)[active],
            [topks[i] for i in active])
        with self._lock:
            # trace bookkeeping first (self-timed): one shared token
            # timestamp per sweep — the whole pool's tokens land
            # together, which is exactly what each caller observes
            self._trace_overhead += obs_cost   # sampler obs (ISSUE 13)
            t_ov = time.perf_counter()
            for i in active:
                req = self.slots[i]
                if req is not None and req.trace is not None:
                    req.trace.event("token", ts=tok_ts,
                                    i=len(req.generated))
            self._trace_overhead += time.perf_counter() - t_ov
            for i in active:
                req = self.slots[i]
                tok = int(toks[i])
                req.generated.append(tok)
                self._last_tokens[i] = tok
                if self._done(req, tok):
                    self.slots[i] = None
                    released = self._retire_slot(i, req)
                    self._finish(req, tok, m, mapped_pages=released)
        return True

    @staticmethod
    def _done(req: ServingRequest, tok: int) -> bool:
        return (req.eos_id is not None and tok == req.eos_id) \
            or len(req.generated) >= req.max_new_tokens

    def _finish(self, req: ServingRequest, last_tok: int, m,
                mapped_pages: int = 0):
        reason = "eos" if (req.eos_id is not None
                           and last_tok == req.eos_id) else "length"
        now = time.perf_counter()
        m["completions"].inc(reason=reason)
        m["latency"].observe(now - req.submitted_ts)
        t_ov = time.perf_counter()
        # per-request final residency (ISSUE 12/14): how much of what
        # it RESERVED this request ever used — the fixed max_len slot
        # under dense slotting, its mapped pages under paging (where
        # the only reservable waste is the last page's tail)
        resident = min(req.prompt.size + len(req.generated),
                       self.engine.max_len)
        if self.paged:
            cap = max(1, mapped_pages) * self._pages.page_len
            ratio = min(1.0, resident / cap)
        else:
            ratio = resident / self.engine.max_len
        m["kv_final"].observe(ratio)
        self._final_res_sum += ratio
        self._final_res_n += 1
        self._close_trace(req, "finish", m, reason=reason,
                          resident_tokens=int(resident),
                          residency_ratio=round(ratio, 6))
        self._trace_overhead += time.perf_counter() - t_ov
        try:
            req.future.set_result(GenerationResult(
                tokens=np.asarray(req.generated, np.int32),
                finish_reason=reason, request_id=req.id,
                ttft_s=(None if req.first_token_ts is None
                        else req.first_token_ts - req.submitted_ts),
                latency_s=now - req.submitted_ts,
                preemptions=req.preemptions))
        except InvalidStateError:
            pass   # the caller gave up on an in-flight request; the
            # pool must keep serving its neighbours regardless

    def _close_trace(self, req: ServingRequest, kind: str, m, **attrs):
        """Terminal trace bookkeeping for one request: terminal event,
        per-request ITL samples into the histogram, black-box record,
        span-tree assembly, SLO accounting."""
        tr = req.trace
        if tr is None:
            return
        tr.event(kind, **attrs)
        summary = tr.summary()    # computed once: histogram + SLO share
        for s in summary["itl_s"]:
            m["itl"].observe(s)
        self.flight_recorder.record_request(tr)
        if self.slo is not None:
            self.slo.observe_summary(summary)
        if self.trace_spans:
            tr.assemble_spans()

    def _record_snapshot(self, m=None, **extra):
        """One flight-recorder snapshot of the scheduler state (called
        per working step, under ``_step_lock``). Carries the KV
        residency accounting (ISSUE 12) so the flight recorder IS the
        memory timeline: allocated vs resident bytes per step ride the
        same black box the crash dump and ``mem_report.py`` read.
        ``m`` is the caller's already-fetched metric map — re-fetching
        per snapshot would pay ~16 registry lookups per step, the
        single biggest avoidable cost against the <2% budget."""
        with self._lock:
            slot_ids = [None if r is None else r.id for r in self.slots]
            queued_ids = [r.id for r in self._queue]
            resident_tokens = sum(
                # a mid-prefill slot is resident only to the tokens its
                # chunks have actually written
                min(r.done_tokens if r.pending is not None
                    else r.prompt.size + len(r.generated),
                    self.engine.max_len)
                for r in self.slots if r is not None)
            # accumulators update under the cheap metadata lock — the
            # lock kv_report/reset_kv_window also take — so a reader
            # never sees a sum without its count, and never waits on
            # device work to see either
            resident = resident_tokens * self._kv_token_bytes
            n_active = sum(s is not None for s in slot_ids)
            if n_active > self._peak_active:
                self._peak_active = n_active
            if self.paged and self._prefix is not None:
                # CoW sharing (ISSUE 16): a shared page must count ONCE
                # — per-slot token sums would bill the same bytes to
                # every slot mapping them. Allocated = pool pages with
                # ≥1 holder (slots OR cache); resident = the per-page
                # fill census, refreshed here for the active slots
                # (cached pages keep the fill they retired with).
                for i, r in enumerate(self.slots):
                    if r is not None:
                        self._pages.note_fill(
                            i, r.done_tokens if r.pending is not None
                            else r.prompt.size + len(r.generated) - 1)
                alloc = self._pages.used_pages * self._kv_page_bytes
                mapped = self._pages.mapped_pages
                resident = min(self._pages.resident_tokens
                               * self._kv_token_bytes, alloc)
            elif self.paged:
                # page granularity (ISSUE 14): allocated = MAPPED pages,
                # not the pool — waste is unfilled page tails only. A
                # just-sampled token is counted resident one sweep before
                # its k/v rows are written (the next sweep's
                # _ensure_pages maps its page first), so at an exact
                # page boundary resident can momentarily exceed the
                # mapping — clamp, or the waste gauge reads negative
                alloc = self._pages.mapped_pages * self._kv_page_bytes
                mapped = self._pages.mapped_pages
                resident = min(resident, alloc)
            else:
                alloc = self._kv_allocated
                mapped = None
            waste = (1.0 - resident / alloc) if alloc else 0.0
            self._kv_last_resident = resident
            self._kv_last_alloc = alloc
            self._kv_resident_sum += resident
            self._kv_alloc_sum += alloc
            self._kv_samples += 1
        if m is None:
            m = self._m()
        m["kv_alloc"].set(float(alloc), replica=self.replica)
        m["kv_res"].set(float(resident), replica=self.replica)
        m["kv_waste"].set(waste, replica=self.replica)
        self._steps += 1
        paged_fields = {} if not self.paged else {
            "kv_mapped_pages": mapped,
            "kv_page_len": self._pages.page_len,
            "kv_pool_bytes": self._kv_allocated,
        }
        if self._prefix is not None:
            # sharing census (ISSUE 16) on every snapshot — the flight
            # recorder doubles as the prefix-cache timeline
            shared = self._pages.shared_pages
            cached = self._prefix.cached_pages
            paged_fields.update(
                kv_used_pages=self._pages.used_pages,
                kv_shared_pages=shared,
                kv_cached_pages=cached,
                kv_cow_copies_total=self._prefix.cow_copies,
                kv_prefix_hits_total=self._prefix.hits,
                kv_prefix_hit_tokens_total=self._prefix.hit_tokens,
            )
            m["kv_shared"].set(float(shared), replica=self.replica)
            m["kv_cached"].set(float(cached), replica=self.replica)
        self.flight_recorder.record_snapshot(
            step=self._steps, slots=slot_ids, queue=queued_ids,
            queue_depth=len(queued_ids),
            occupancy=n_active / self.n_slots,
            kv_allocated_bytes=alloc,
            kv_resident_bytes=resident,
            kv_token_bytes=self._kv_token_bytes,
            kv_waste_ratio=round(waste, 6),
            **paged_fields, **extra)

    def _debug_extra(self):
        """Live state merged into ``flight_recorder.debug_state()`` —
        what ``GET /debug/serving`` shows beyond the recorded past."""
        with self._lock:
            state = {
                "n_slots": self.n_slots,
                "occupancy": sum(r is not None for r in self.slots)
                / self.n_slots,
                "queue_depth": len(self._queue),
                "slots": [None if r is None else r.id
                          for r in self.slots],
                "steps": self._steps,
                "trace_overhead_seconds": round(self._trace_overhead, 6),
            }
        state["kv"] = self.kv_report()
        if hasattr(self.engine, "compile_report"):
            state["compiles"] = self.engine.compile_report()
        if self.slo is not None:
            state["slo"] = self.slo.report()
        return state

    # ---------------------------------------------------- inspection
    @property
    def trace_overhead_seconds(self) -> float:
        """Cumulative host cost of the SLO-plane bookkeeping (trace
        events, snapshots, trace close-out) — the MetricsListener-style
        self-timing the <2% budget test asserts against."""
        return self._trace_overhead

    def occupancy(self) -> float:
        with self._lock:
            return sum(r is not None for r in self.slots) / self.n_slots

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def cache_nbytes(self) -> int:
        return kvcache.cache_nbytes(self.cache)

    def reset_kv_window(self):
        """Restart the KV residency accumulators (running means and
        final-residency samples). Benches call this after warm-up, next
        to swapping in a fresh SLOTracker, so the memory evidence and
        the SLO evidence in one row cover the SAME measured window —
        warm-up's near-empty pool would otherwise bias the waste ratio
        upward. Gauges and flight-recorder snapshots are untouched.

        Takes the metadata ``_lock`` — the lock every accumulator
        writer holds (``_record_snapshot`` updates inside its locked
        block; ``_finish`` runs inside the admit/sweep locked blocks) —
        so a reset never lands between a sum and its count, and never
        waits out a device dispatch."""
        with self._lock:
            self._kv_resident_sum = 0.0
            self._kv_alloc_sum = 0.0
            self._kv_samples = 0
            self._final_res_sum = 0.0
            self._final_res_n = 0
            self._peak_active = 0
        return self

    def kv_report(self) -> dict:
        """KV residency accounting (ISSUE 12), plain data: allocated vs
        resident bytes, running-mean waste ratio over the serve since
        construction (or the last ``reset_kv_window``), per-token
        bytes, and mean final residency. This is the block ``bench.py``
        embeds as a row's ``memory`` evidence and ``GET /debug/memory``
        aggregates across live schedulers.

        Reads under the metadata ``_lock`` — the writers' lock — so a
        live report never sees a sum without its count, and a debug
        endpoint never blocks on an in-flight device sweep (the PR-11
        discipline: device work runs outside the metadata lock)."""
        with self._lock:
            return self._kv_report_locked()

    def _kv_report_locked(self) -> dict:
        # allocated bytes: static pool footprint under dense slotting;
        # MAPPED-page bytes under paging (ISSUE 14) — last snapshot and
        # the window sum, so mean waste is resident-sum over alloc-sum
        # (a ratio of same-window totals, not of mismatched means)
        mean_res = (self._kv_resident_sum / self._kv_samples
                    if self._kv_samples else 0.0)
        if self.paged:
            alloc_last = self._kv_last_alloc
            mean_alloc = (self._kv_alloc_sum / self._kv_samples
                          if self._kv_samples else 0.0)
            waste_mean = (1.0 - self._kv_resident_sum / self._kv_alloc_sum
                          if self._kv_alloc_sum else 0.0)
        else:
            alloc_last = mean_alloc = self._kv_allocated
            waste_mean = (1.0 - mean_res / self._kv_allocated
                          if self._kv_allocated else 0.0)
        out = {
            "allocated_bytes": alloc_last,
            "allocated_bytes_mean": round(mean_alloc, 1),
            "pool_bytes": self._kv_allocated,
            "token_bytes": self._kv_token_bytes,
            "resident_bytes_last": self._kv_last_resident,
            "resident_bytes_mean": round(mean_res, 1),
            "waste_ratio_last": round(1.0 - self._kv_last_resident
                                      / alloc_last, 6) if alloc_last
            else 0.0,
            "waste_ratio_mean": round(waste_mean, 6),
            "snapshots": self._kv_samples,
            "peak_concurrent": self._peak_active,
            "final_residency_mean": round(
                self._final_res_sum / self._final_res_n, 6)
            if self._final_res_n else None,
            "finished_requests": self._final_res_n,
        }
        if self.paged:
            out["paged"] = self._pages.report()
            out["kv_dtype"] = ("int8"
                               if kvcache.is_quantized(self.cache)
                               else str(jnp.dtype(
                                   self.cache["k"].dtype).name))
        if self._prefix is not None:
            # sharing evidence (ISSUE 16): hits, tokens the pool did
            # NOT re-prefill or re-store, CoW splits, evictions
            out["prefix"] = self._prefix.report()
        return out

    def drop_session(self, session_id: str) -> bool:
        """Release a session's retained pages (end of conversation) —
        they become plain cached prefix pages if the block index still
        holds them, else free. Returns True if the session existed."""
        with self._lock:
            if self._prefix is None:
                return False
            return self._prefix.drop_session(session_id)

    def check_pages(self) -> bool:
        """Assert the free-XOR-refcounted page invariant, feeding the
        prefix cache's hold census in as the external refs (the fuzz
        tests' oracle). True for dense pools."""
        with self._lock:
            if not self.paged:
                return True
            return self._pages.check(
                self._prefix.holds() if self._prefix is not None
                else None)
