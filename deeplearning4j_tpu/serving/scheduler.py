"""Continuous-batching inference scheduler over a fixed decode-slot pool.

The μ-cuDNN idea (PAPERS.md, arXiv 1804.04806) applied to serving: keep
the device sweep FULL by slicing admission into fixed-capacity slots
instead of reshaping the batch around each request. One
``GenerationEngine`` cache holds ``n_slots`` sequences; the scheduler
loop interleaves

    admit:  free slot + queued request → jitted per-slot prefill
            (neighbour slots keep decoding state untouched), first
            token sampled from the prefill logits (this is TTFT)
    decode: ONE jitted sweep advances every active slot a token —
            per-slot temperature/top-k vectors let mixed requests share
            the sweep; finished slots free immediately for re-admission

so mixed-length traffic never drains the pool to prefill and a finished
request never strands its neighbours. Each request resolves a
``concurrent.futures.Future`` with a :class:`GenerationResult`.

Preemption (optional, ``starvation_ms``): when the queue head has waited
past the deadline and no slot is free, the active request with the most
REMAINING budget is preempted — its slot frees, its context
(prompt + generated so far) re-queues and is later re-prefilled
(vLLM-style recompute preemption). Greedy decoding is preemption-
transparent: prefill(prompt+generated) reproduces the exact logits the
interrupted decode would have seen (the engine's equivalence guarantee),
so the output is unchanged.

Telemetry rides the unified plane (``dl4j_serving_*`` on the process
registry, spans on the tracer): slot occupancy, queue depth, TTFT /
queue-wait / request-latency histograms, decode-step timing, token and
preemption counters. ``scripts/check_metric_names.py`` lints the sites.

The SLO plane (ISSUE 11) rides on top, host-side only — the device
dispatch sequence is untouched, so greedy scheduler output stays
bit-identical to ``generate()`` with everything below enabled:

- every request carries an ``obs.RequestTrace`` lifecycle timeline
  (submit → queue → admit → prefill → each token → preempt/requeue →
  finish/cancel/fail), stitched into the span tracer on completion and
  feeding the ``dl4j_serving_itl_seconds`` inter-token-latency
  histogram PER REQUEST — a preemption's requeue gap is one (large)
  ITL sample, invisible to per-sweep timing;
- a bounded :class:`~..obs.FlightRecorder` black box keeps the last N
  completed traces + per-step scheduler snapshots (slot map, queue,
  occupancy), dumped as JSONL on demand and automatically when the
  serve loop crashes (``_fail_all``), and served live at
  ``GET /debug/serving`` / ``GET /debug/requests``;
- pass ``slo=SLOConfig(...)`` to account rolling goodput / attainment
  / burn-rate (``dl4j_slo_*`` gauges, ``scheduler.slo.report()``);
- point-in-time gauges carry a ``replica`` label (default ``"0"``) so
  the multi-host router (ROADMAP item 2) reads per-replica load
  unchanged.

The memory & compile plane (ISSUE 12) rides the same host-side-only
contract: a construction-time memory census (params + KV under this
replica's label), per-step KV residency accounting —
``dl4j_kv_allocated_bytes`` vs ``dl4j_kv_resident_bytes`` and the
``dl4j_kv_waste_ratio`` that sizes the paged-KV PR, resident counts
taken from the host-side ``prompt+generated`` mirrors (never a device
fetch) — a per-request ``dl4j_kv_final_residency_ratio`` histogram at
completion, and residency fields on every flight-recorder snapshot so
the black box doubles as the memory timeline (``kv_report()`` /
``GET /debug/memory`` / ``scripts/mem_report.py``). The engine's
jitted entry points sit behind compile sentinels; after
``engine.mark_warm()`` any recompile warns and counts
(``dl4j_compile_retraces_total``).

The trace bookkeeping self-times (``trace_overhead_seconds``, the
MetricsListener precedent); tests pin it under 2% of the decode-sweep
wall clock — with census, sentinel, and residency accounting all on.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import (FlightRecorder, RequestTrace, SLOConfig, SLOTracker,
                   get_registry, span)
from . import kvcache, workloads
from .engine import GenerationEngine
from .workloads import (BeamResult, BeamState, EmbedResult, RequestKind,
                        ScoreResult)


@dataclass
class GenerationResult:
    """What a request's future resolves to."""
    tokens: np.ndarray          # generated ids, prompt excluded
    finish_reason: str          # "eos" | "length"
    request_id: int
    ttft_s: Optional[float]     # submit → first token
    latency_s: float            # submit → completion
    preemptions: int


@dataclass
class ServingRequest:
    id: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float
    top_k: int
    eos_id: Optional[int]
    future: Future
    submitted_ts: float
    queued_ts: float            # reset on re-queue after preemption
    first_token_ts: Optional[float] = None
    generated: List[int] = field(default_factory=list)
    preemptions: int = 0
    trace: Optional[RequestTrace] = None
    # chunked-prefill state (ISSUE 14, paged mode): the context being
    # prefilled this admission and how many of its tokens are written;
    # ``pending is None`` means the slot is decoding (or dense mode)
    pending: Optional[np.ndarray] = None
    done_tokens: int = 0
    prefill_s: float = 0.0      # summed chunk wall time, this admission
    chunks: int = 0             # chunks dispatched, this admission
    # prefix sharing (ISSUE 16): the session this request extends (its
    # finish retains pages under the same id), and the tokens the last
    # admission skipped via shared resident pages
    session_id: Optional[str] = None
    prefix_matched: int = 0
    # multi-workload plane (ISSUE 20): the typed-request knobs and the
    # per-kind in-flight state the scheduler accumulates host-side
    kind: RequestKind = RequestKind.GENERATE
    beam_width: int = 0
    pooling: str = "mean"
    token_mask: Optional[workloads.TokenMask] = None
    beam: Optional[BeamState] = None
    score_lps: List[float] = field(default_factory=list)
    embed_acc: Optional[np.ndarray] = None
    embed_last: Optional[np.ndarray] = None
    released_pages: int = 0

    def context(self) -> np.ndarray:
        """Token ids to prefill on (re-)admission: the original prompt
        plus everything generated so far (recompute preemption)."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def remaining(self) -> int:
        done = (self.beam.progress() if self.beam is not None
                else len(self.generated))
        return self.max_new_tokens - done


class ContinuousBatchingScheduler:
    """Slot-based admission + full-pool decode over one engine cache.

    Synchronous core: ``step()`` performs one admit+decode iteration and
    is what tests script; ``run_until_idle()`` loops it; ``start()`` /
    ``stop()`` run the same loop on a daemon thread for callers that
    ``submit`` from elsewhere. Metadata (queue/slots) lives under a
    short-held lock so submit never waits on device work; a second lock
    serializes step() iterations (the cache is donated — one dispatch
    at a time). A request whose Future is cancelled while queued is
    dropped before it costs a prefill.
    """

    def __init__(self, engine: GenerationEngine, n_slots: int = 4, *,
                 starvation_ms: Optional[float] = None, key=None,
                 replica: str = "0",
                 slo: Union[SLOConfig, SLOTracker, None] = None,
                 recorder_requests: int = 256,
                 recorder_snapshots: int = 512,
                 crash_dump_path: Optional[str] = None,
                 trace_spans: bool = True,
                 sample_obs_every: int = 32,
                 page_len: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefix_cache: bool = False,
                 quant_kv: Optional[str] = None):
        if n_slots < 1:
            raise ValueError("need at least one decode slot")
        if prefix_cache and page_len is None and n_pages is None:
            raise ValueError("prefix_cache rides the paged pool: give "
                             "page_len and/or n_pages")
        if quant_kv is not None and page_len is None and n_pages is None:
            raise ValueError("quant_kv quantizes the paged pool: give "
                             "page_len and/or n_pages")
        self.engine = engine
        self.n_slots = int(n_slots)
        self.starvation_ms = starvation_ms
        self.replica = str(replica)
        # paged mode (ISSUE 14): give EITHER knob and the pool becomes
        # block-paged — n_pages shared fixed-size pages + a per-slot
        # page table instead of n_slots × max_len dense rows. Admission
        # turns page-availability-based, long prompts prefill in
        # engine.chunk_len chunks interleaved with decode sweeps, and
        # preemption/cancel/finish return pages to the free list.
        # n_pages defaults to full per-slot capacity (no
        # oversubscription); size it DOWN to serve at actual token
        # residency — that is the point (the serving/tune.py sweep and
        # bench rows pick the byte budget).
        self.paged = page_len is not None or n_pages is not None
        # sampler observability (ISSUE 13): every Nth sampling event
        # (decode sweeps and admission first-tokens share one
        # counter), derive next-token entropy + top-k truncated mass
        # host-side from the logits that event produced (0 disables;
        # 1 = every event). Each observation is one (active, V) fetch
        # + a numpy softmax; the default subsamples aggressively
        # because the serving trace budget (<2% of the sweep wall,
        # tests pin it) has little headroom on tiny models — fidelity
        # work that wants every sweep sets 1 explicitly. Counted into
        # trace_overhead_seconds.
        self.sample_obs_every = max(0, int(sample_obs_every))
        self._obs_events = 0
        if self.paged:
            plen = int(page_len if page_len is not None
                       else kvcache.DEFAULT_PAGE_LEN)
            per_slot = -(-engine.max_len // plen)
            np_ = int(n_pages if n_pages is not None
                      else self.n_slots * per_slot)
            # int8 KV storage (ISSUE 19): quant_kv pins the mode
            # (off|on|auto|race); None defers to the engine / env
            # ladder inside serving.quant.decide_kv, whose verdict is
            # the fidelity-gated promotion race. Every path below —
            # CoW splits, prefix sharing, re-prefill, preemption —
            # is mode-blind: scales ride the page axis.
            if quant_kv is not None:
                from . import quant
                qz = quant.decide_kv(engine, self.n_slots, np_, plen,
                                     mode=quant_kv) == "int8"
                self.cache = engine.init_paged_cache(
                    self.n_slots, np_, plen, quantized=qz)
            else:
                self.cache = engine.init_paged_cache(self.n_slots, np_,
                                                     plen)
            self._pages: Optional[kvcache.PageTable] = \
                kvcache.PageTable.for_cache(self.cache)
            self._kv_page_bytes = kvcache.page_nbytes(self.cache)
        else:
            self.cache = engine.init_cache(self.n_slots)
            self._pages = None
            self._kv_page_bytes = 0
        # copy-on-write prefix sharing (ISSUE 16, opt-in): a radix-style
        # index + session retention over the page pool. Admission maps
        # matched prefixes into the new slot's table (zero jitted
        # changes — the gather reads arbitrary page sets) and prefills
        # only the tail; a slot about to scatter into a shared page
        # splits it first via engine.copy_page.
        self._prefix: Optional[kvcache.PrefixCache] = \
            kvcache.PrefixCache(self._pages) if prefix_cache else None
        if self._prefix is not None and hasattr(engine, "copy_page"):
            # warm the CoW page-copy kernel NOW (a src==dst self-copy is
            # a semantic no-op): the first real split may land after
            # mark_warm(), and it must not count as a retrace
            self.cache = engine.copy_page(self.cache, 0, 0)
        if hasattr(engine, "sample_masked"):
            # CONSTRAINED decoding (ISSUE 20): warm the masked sampler
            # for both sampling shapes — the pool sweep (n_slots, V)
            # and the admission first-token (1, V) — so the first
            # grammar step after mark_warm() is never a retrace
            vocab = int(engine.cfg.vocab_size)
            wkey = jax.random.PRNGKey(0)
            for b in {self.n_slots, 1}:
                engine.sample_masked(
                    wkey, jnp.zeros((b, vocab), jnp.float32),
                    np.zeros((b,), np.float32), np.zeros((b,), np.int32),
                    np.ones((b, vocab), bool))
        # memory plane (ISSUE 12/14): allocated bytes are static under
        # dense slotting (slots × max_len) and MAPPED-page bytes under
        # paging; resident bytes follow the per-slot token counts the
        # scheduler already tracks host-side (prompt + generated — no
        # device fetch on the hot path)
        self._kv_allocated = kvcache.cache_nbytes(self.cache)
        self._kv_token_bytes = kvcache.token_nbytes(self.cache)
        self._kv_last_resident = 0
        self._kv_last_alloc = 0 if self.paged else self._kv_allocated
        self._kv_resident_sum = 0.0
        self._kv_alloc_sum = 0.0
        self._kv_samples = 0
        self._final_res_sum = 0.0
        self._final_res_n = 0
        # peak concurrent active requests over the accounting window —
        # the ≥2×-concurrency-at-equal-bytes evidence the paged bench
        # row reports (ISSUE 14)
        self._peak_active = 0
        # last-published per-kind active census (ISSUE 20): the gauge
        # write is the expensive half, so snapshots publish deltas only
        # — the steady single-kind serve pays ~0 sets/step, not 5,
        # which keeps the census inside the <2% bookkeeping budget
        self._kind_census_pub: Dict[str, int] = {}
        self._kv_pub_alloc: Optional[float] = None   # last published
        self.slots: List[Optional[ServingRequest]] = [None] * self.n_slots
        self._queue: deque = deque()
        self._draining = False      # drain(): admission gate (ISSUE 18)
        # two locks: `_lock` guards the cheap metadata (queue, slots,
        # key, last_tokens) so submit()/inspection never wait on device
        # work; `_step_lock` serializes whole step() iterations — the
        # cache is donated through prefill/decode, so two concurrent
        # steps would hand the same buffer to XLA twice
        self._lock = threading.RLock()
        self._step_lock = threading.Lock()
        self._key = jax.random.PRNGKey(0) if key is None else key
        self._last_tokens = np.zeros((self.n_slots,), np.int32)
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # SLO plane (ISSUE 11): black box + per-request traces + SLO
        self.flight_recorder = FlightRecorder(
            capacity_requests=recorder_requests,
            capacity_snapshots=recorder_snapshots, replica=self.replica,
            crash_dump_path=crash_dump_path)
        self.flight_recorder.extra_state = self._debug_extra
        if isinstance(slo, SLOTracker):
            self.slo: Optional[SLOTracker] = slo
        elif slo is not None:
            self.slo = SLOTracker(slo, replica=self.replica)
        else:
            self.slo = None
        self.trace_spans = trace_spans
        self._steps = 0
        self._trace_overhead = 0.0
        # publish the pool's memory census once (construction, not hot
        # path): params + KV attribution under this replica's label,
        # and the static allocated-bytes gauge. Decoration only — a
        # census failure (e.g. a user metric squatting on the name with
        # other labels) must not take down serving.
        try:
            from ..obs import memory as obs_memory
            obs_memory.emit_census(
                {"params": engine.params, "kv_cache": self.cache},
                replica=self.replica, source="serving")
            m = self._m()
            m["kv_alloc"].set(float(self._kv_last_alloc),
                              replica=self.replica)
        except Exception:  # noqa: BLE001 — census is decoration
            pass

    # ------------------------------------------------------- metrics
    @staticmethod
    def _m():
        reg = get_registry()
        return {
            "requests": reg.counter(
                "dl4j_serving_requests_total",
                "Requests submitted to the continuous-batching scheduler"),
            "completions": reg.counter(
                "dl4j_serving_completions_total",
                "Requests completed, by finish reason",
                labelnames=("reason",)),
            "preemptions": reg.counter(
                "dl4j_serving_preemptions_total",
                "Active requests preempted (recompute on re-admission)"),
            "prefills": reg.counter(
                "dl4j_serving_prefills_total",
                "Per-slot prefill admissions (includes re-admissions)"),
            "decode_steps": reg.counter(
                "dl4j_serving_decode_steps_total",
                "Full-pool decode sweeps executed"),
            "tokens": reg.counter(
                "dl4j_serving_tokens_total",
                "Tokens generated across all requests"),
            # multi-workload census (ISSUE 20): the same request flow,
            # broken down by RequestKind — capacity planning reads
            # these to see WHAT the pool serves, not just how much
            "wl_requests": reg.counter(
                "dl4j_workload_requests_total",
                "Requests submitted, by workload kind",
                labelnames=("kind",)),
            "wl_completions": reg.counter(
                "dl4j_workload_completions_total",
                "Requests completed (finish path), by workload kind",
                labelnames=("kind",)),
            "wl_tokens": reg.counter(
                "dl4j_workload_tokens_total",
                "Tokens processed per workload kind: generated tokens "
                "for generate/constrained, beam candidates for beam, "
                "prompt tokens scored/pooled for score/embed",
                labelnames=("kind",)),
            "active_kind": reg.gauge(
                "dl4j_serving_active_requests",
                "Admitted in-flight requests at the last snapshot, by "
                "workload kind (a beam group counts once)",
                labelnames=("replica", "kind")),
            "occupancy": reg.gauge(
                "dl4j_serving_slot_occupancy",
                "Active slots / pool size at the last decode sweep "
                "(0 when the pool is idle)",
                labelnames=("replica",)),
            "queue_depth": reg.gauge(
                "dl4j_serving_queue_depth",
                "Requests waiting for a decode slot",
                labelnames=("replica",)),
            "tokens_per_s": reg.gauge(
                "dl4j_serving_tokens_per_second",
                "Generated tokens per second over the last decode sweep "
                "(0 when the pool is idle)",
                labelnames=("replica",)),
            "ttft": reg.histogram(
                "dl4j_serving_ttft_seconds",
                "Time from submit to first generated token"),
            "queue_wait": reg.histogram(
                "dl4j_serving_queue_wait_seconds",
                "Time a request waited in the admission queue"),
            "decode_s": reg.histogram(
                "dl4j_serving_decode_step_seconds",
                "Wall time of one full-pool decode sweep"),
            "itl": reg.histogram(
                "dl4j_serving_itl_seconds",
                "Inter-token latency, derived per request from its "
                "lifecycle trace (a preemption requeue gap is one "
                "sample)"),
            "latency": reg.histogram(
                "dl4j_serving_request_latency_seconds",
                "Time from submit to request completion"),
            # KV residency accounting (ISSUE 12/14): allocated vs
            # resident bytes — dense slots allocate max_len per slot,
            # the paged pool allocates only MAPPED pages
            "kv_alloc": reg.gauge(
                "dl4j_kv_allocated_bytes",
                "Allocated KV bytes: slots x max_len (dense slotting) "
                "or mapped pages x page bytes (paged pool)",
                labelnames=("replica",)),
            "kv_res": reg.gauge(
                "dl4j_kv_resident_bytes",
                "KV bytes actually holding tokens (active slots' "
                "prompt+generated counts x per-token bytes)",
                labelnames=("replica",)),
            "kv_waste": reg.gauge(
                "dl4j_kv_waste_ratio",
                "1 - resident/allocated (dense idle pool = 1.0; paged "
                "counts mapped pages, so waste is only unfilled page "
                "tails)", labelnames=("replica",)),
            # CoW prefix sharing census (ISSUE 16) — shared pages count
            # ONCE in kv_alloc above; these expose the sharing itself
            "kv_shared": reg.gauge(
                "dl4j_kv_shared_pages",
                "Pool pages with more than one holder (slot mappings + "
                "prefix-cache/session holds) at the last snapshot",
                labelnames=("replica",)),
            "kv_cached": reg.gauge(
                "dl4j_kv_cached_pages",
                "Pool pages resident only because the prefix cache "
                "holds them — the LRU-evictable reclaim headroom",
                labelnames=("replica",)),
            "kv_cow": reg.counter(
                "dl4j_kv_cow_copies_total",
                "Copy-on-write page splits (device page copies) before "
                "a slot scattered into a shared page"),
            "kv_prefix_hits": reg.counter(
                "dl4j_kv_prefix_hits_total",
                "Admissions that mapped a shared resident prefix "
                "instead of re-prefilling it"),
            "kv_prefix_hit_tokens": reg.counter(
                "dl4j_kv_prefix_hit_tokens_total",
                "Prompt tokens skipped at prefill because their pages "
                "were already resident (prefix/session hits)"),
            "kv_prefix_evictions": reg.counter(
                "dl4j_kv_prefix_evictions_total",
                "Cached prefix pages freed by LRU eviction under page "
                "pressure (before the preemption path)"),
            "kv_final": reg.histogram(
                "dl4j_kv_final_residency_ratio",
                "Per-request final residency at completion: "
                "(prompt+generated) / max_len under dense slotting, "
                "/ mapped-page capacity under paging — how much of "
                "what it reserved a request ever used",
                buckets=tuple(i / 20 for i in range(1, 21))),
            # sampler observability (ISSUE 13): health of the model's
            # next-token distribution at the sampling sites — a
            # quantized KV cache or int8 weights (ROADMAP 3) that
            # flattens or spikes it shows up here first
            "sample_entropy": reg.histogram(
                "dl4j_serving_sample_entropy",
                "Per-observation mean entropy (nats) of the MODEL's "
                "next-token distribution (softmax at temperature 1, "
                "before per-request temperature/top-k shaping) over "
                "active slots — the sharpness signal quantization "
                "drift shows up in, meaningful for greedy pools too",
                buckets=tuple(0.25 * i for i in range(1, 61))),
            "topk_mass": reg.histogram(
                "dl4j_serving_topk_mass",
                "Per-observation mean probability mass (at temperature "
                "1) the top-k truncation keeps, over active slots with "
                "top_k > 0",
                buckets=tuple(i / 20 for i in range(1, 21))),
        }

    # -------------------------------------------------------- submit
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None,
               session_id: Optional[str] = None,
               kind=RequestKind.GENERATE, beam_width: int = 0,
               pooling: str = "mean", token_mask=None,
               **extra) -> Future:
        """Queue a typed serving request; returns a Future resolving to
        a :class:`GenerationResult` (GENERATE / CONSTRAINED), a
        :class:`~.workloads.ScoreResult` (SCORE), an
        :class:`~.workloads.EmbedResult` (EMBED) or a
        :class:`~.workloads.BeamResult` (BEAM). Everything that could
        never run — malformed prompts, unknown kwargs, knobs on the
        wrong kind, capacity overruns — fails HERE with a ValueError,
        so admission never has to partially honour a request.

        Kinds (ISSUE 20; ``kind`` accepts the enum, its string value,
        or the fleet wire byte):

        - ``GENERATE`` — the classic continuation path, unchanged;
        - ``SCORE`` — prefill-only per-token logprobs + perplexity of
          the prompt itself (paged pool; ``max_new_tokens`` ignored);
        - ``EMBED`` — pooled post-``ln_f`` hidden state of the prompt
          (``pooling``: "mean" | "last"; paged pool; prefill-only);
        - ``BEAM`` — width-``beam_width`` (default 4) beam search;
          needs ``beam_width`` free lanes and the paged pool, where the
          beams share the prompt's pages copy-on-write;
        - ``CONSTRAINED`` — ``token_mask`` gates every sampled token:
          a fixed (V,) bool allow-array or a callback
          ``step(generated_ids) -> (V,) bool`` (grammar stepping).

        ``session_id`` (ISSUE 16, needs ``prefix_cache=True``) threads a
        multi-turn conversation: at finish the request's written pages
        are RETAINED under the id, and the next ``submit`` whose prompt
        extends the retained context maps those pages instead of
        re-prefilling the history — the new turn's delta becomes
        append-only. Each turn's retention supersedes the last;
        :meth:`drop_session` releases it explicitly."""
        if extra:
            raise ValueError(
                f"submit() got unknown keyword argument(s) "
                f"{sorted(extra)}; valid: temperature, top_k, eos_id, "
                "session_id, kind, beam_width, pooling, token_mask")
        kind = RequestKind.coerce(kind)
        raw = np.asarray(prompt_ids)
        if raw.size and not np.issubdtype(raw.dtype, np.integer):
            raise ValueError("prompt_ids must be integer token ids "
                             f"(got dtype {raw.dtype})")
        prompt = raw.reshape(-1).astype(np.int32)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        vocab = int(self.engine.cfg.vocab_size)
        if int(prompt.min()) < 0 or int(prompt.max()) >= vocab:
            raise ValueError(
                f"prompt ids outside the vocabulary [0, {vocab})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # knobs on the wrong kind fail loudly rather than silently
        # doing nothing — the typed plane's whole point
        if beam_width and kind is not RequestKind.BEAM:
            raise ValueError("beam_width is a BEAM knob "
                             f"(got kind={kind.value!r})")
        if token_mask is not None and kind is not RequestKind.CONSTRAINED:
            raise ValueError("token_mask is a CONSTRAINED knob "
                             f"(got kind={kind.value!r})")
        if pooling != "mean" and kind is not RequestKind.EMBED:
            raise ValueError("pooling is an EMBED knob "
                             f"(got kind={kind.value!r})")
        if session_id is not None:
            if self._prefix is None:
                raise ValueError("session_id needs prefix_cache=True "
                                 "(and the paged pool)")
            if kind not in (RequestKind.GENERATE,
                            RequestKind.CONSTRAINED):
                raise ValueError("session_id threads multi-turn "
                                 "generate/constrained requests only "
                                 f"(got kind={kind.value!r})")
        if kind in (RequestKind.SCORE, RequestKind.EMBED,
                    RequestKind.BEAM) and not self.paged:
            raise ValueError(f"{kind.value} requests need the paged "
                             "pool (pass page_len and/or n_pages)")
        if kind is RequestKind.SCORE and prompt.size < 2:
            raise ValueError("scoring needs at least 2 tokens "
                             "(position 0 is unconditional)")
        if kind is RequestKind.EMBED \
                and pooling not in workloads.POOLING_WIRE:
            raise ValueError(f"unknown pooling {pooling!r}; expected "
                             f"one of {sorted(workloads.POOLING_WIRE)}")
        if kind is RequestKind.CONSTRAINED:
            if token_mask is None:
                raise ValueError("constrained decoding needs "
                                 "token_mask (array or callback)")
            if not callable(token_mask):
                # validate + normalize fixed masks once, at the edge
                token_mask = workloads.resolve_mask(token_mask, [],
                                                    vocab)
        if kind is RequestKind.BEAM:
            beam_width = int(beam_width) or 4
            if not 1 <= beam_width <= self.n_slots:
                raise ValueError(
                    f"beam_width {beam_width} outside "
                    f"[1, n_slots={self.n_slots}] — the whole group "
                    "admits together")
            if temperature > 0 or top_k > 0:
                raise ValueError("beam search ranks exact log-probs; "
                                 "temperature/top_k do not apply")
        else:
            beam_width = 0
        if kind in (RequestKind.SCORE, RequestKind.EMBED):
            # prefill-only: the request retires at its final chunk and
            # every prompt row's k/v is written (capacity = prompt)
            max_new_tokens = 1
            total = int(prompt.size)
        else:
            total = prompt.size + max_new_tokens - 1
        if total > self.engine.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + budget = {total} exceeds "
                f"the slot capacity max_len={self.engine.max_len}")
        if self.paged:
            full = self._pages.pages_for(total)
            if kind is RequestKind.BEAM:
                # fan-out feasibility: the prompt's FULL pages are
                # shared (one copy across the group), only the
                # divergent tail is per-beam
                shr = prompt.size // self._pages.page_len
                need = shr + beam_width * (full - shr)
                if need > self._pages.n_pages:
                    raise ValueError(
                        f"beam fan-out needs {need} pages ({shr} "
                        f"shared prefix + {beam_width} x {full - shr} "
                        f"divergent) but the pool holds "
                        f"{self._pages.n_pages}")
            elif full > self._pages.n_pages:
                raise ValueError(
                    f"request needs {full} pages ({total} tokens at "
                    f"page_len={self._pages.page_len}) but the pool "
                    f"holds {self._pages.n_pages} — it could never "
                    "run even alone")
        now = time.perf_counter()
        fut: Future = Future()
        with self._lock:
            if self._draining:
                raise RuntimeError("scheduler is draining — submit to "
                                   "another replica")
            req = ServingRequest(
                id=self._next_id, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), top_k=int(top_k),
                eos_id=eos_id, future=fut, submitted_ts=now,
                queued_ts=now, session_id=session_id, kind=kind,
                beam_width=beam_width, pooling=pooling,
                token_mask=token_mask)
            if kind is RequestKind.BEAM:
                req.beam = BeamState(width=beam_width)
            req.trace = RequestTrace(request_id=req.id,
                                     replica=self.replica,
                                     kind=kind.value)
            req.trace.event("submit", ts=now,
                            prompt_tokens=int(prompt.size),
                            max_new_tokens=int(max_new_tokens))
            req.trace.event("queue", ts=now)
            self._next_id += 1
            self._queue.append(req)
            m = self._m()
            m["requests"].inc()
            m["wl_requests"].inc(kind=kind.value)
            m["queue_depth"].set(len(self._queue), replica=self.replica)
        return fut

    # ---------------------------------------------------------- step
    def step(self) -> bool:
        """One scheduler iteration: preempt-if-starved, admit, decode.
        Returns True if any work happened (False = fully idle).

        Device work (prefill, the decode sweep, any compile it
        triggers) runs OUTSIDE the metadata lock — a client thread's
        submit() never waits on a sweep — while ``_step_lock``
        serializes iterations so the donated cache is never dispatched
        twice."""
        with self._step_lock:
            m = self._m()
            with self._lock:
                did = self._maybe_preempt(m)
                admissions = self._pop_admissions(m)
            if self.paged:
                # chunked prefill (ISSUE 14): every prefilling slot —
                # just admitted or mid-prompt — advances ONE chunk,
                # then the decode sweep runs; a T=4096 admission costs
                # each sweep a chunk-sized pause, never the whole
                # prompt
                did = self._advance_prefills(m) or did
            else:
                for slot, req in admissions:
                    self._admit_one(slot, req, m)
            did = did or bool(admissions)
            did = self._decode_sweep(m) or did
            with self._lock:
                m["queue_depth"].set(len(self._queue),
                                     replica=self.replica)
            if did:
                t_ov = time.perf_counter()
                self._record_snapshot(m)
                self._trace_overhead += time.perf_counter() - t_ov
            else:
                # idle reset: the occupancy/throughput gauges used to
                # freeze at their last busy value after the pool
                # drained — a router reading them would keep routing
                # around a replica that is actually free. Residency
                # drains with it: an idle fixed pool is 100% waste.
                m["occupancy"].set(0.0, replica=self.replica)
                m["tokens_per_s"].set(0.0, replica=self.replica)
                # dense idle = 100% waste (max_len × slots preallocated
                # for nothing); paged idle maps NOTHING — zero
                # allocated, zero wasted, which is the whole point.
                # With the prefix cache, idle residency is whatever the
                # cache still HOLDS (ISSUE 16): cached pages occupy
                # real pool bytes until evicted, and the gauges must
                # say so.
                if self.paged and self._prefix is not None:
                    with self._lock:
                        alloc = self._pages.used_pages \
                            * self._kv_page_bytes
                        resident = min(
                            alloc, self._pages.resident_tokens
                            * self._kv_token_bytes)
                        self._kv_last_resident = resident
                        self._kv_last_alloc = alloc
                        self._kv_pub_alloc = alloc
                    m["kv_alloc"].set(float(alloc), replica=self.replica)
                    m["kv_res"].set(float(resident),
                                    replica=self.replica)
                    m["kv_waste"].set(
                        (1.0 - resident / alloc) if alloc else 0.0,
                        replica=self.replica)
                    m["kv_cached"].set(float(self._prefix.cached_pages),
                                       replica=self.replica)
                    m["kv_shared"].set(float(self._pages.shared_pages),
                                       replica=self.replica)
                else:
                    m["kv_res"].set(0.0, replica=self.replica)
                    if self.paged:
                        m["kv_alloc"].set(0.0, replica=self.replica)
                        with self._lock:
                            self._kv_pub_alloc = 0
                        m["kv_waste"].set(0.0, replica=self.replica)
                    else:
                        m["kv_waste"].set(1.0, replica=self.replica)
                    with self._lock:   # writers-hold-_lock invariant
                        self._kv_last_resident = 0
                        if self.paged:
                            self._kv_last_alloc = 0
        return did

    def run_until_idle(self, max_steps: int = 100000):
        """Drive step() until queue and pool are empty (tests, batch
        jobs). ``max_steps`` is a runaway guard, generous vs any real
        trace (one step ≥ one token for every active slot)."""
        for _ in range(max_steps):
            with self._lock:
                idle = not self._queue and not any(self.slots)
            if idle:
                return
            self.step()
        raise RuntimeError(f"scheduler not idle after {max_steps} steps")

    # ---------------------------------------------------- background
    def start(self, poll_s: float = 0.001):
        """Serve from a daemon thread until stop(): step() when there is
        work, sleep ``poll_s`` when idle. The thread is stopped at
        interpreter exit if still running — a daemon thread caught
        mid-decode while jax tears down aborts the process."""
        if self._thread is not None:
            return self
        if not getattr(self, "_atexit_registered", False):
            import atexit
            import weakref
            ref = weakref.ref(self)
            atexit.register(lambda: (lambda s: s and s.stop())(ref()))
            self._atexit_registered = True
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.is_set():
                try:
                    worked = self.step()
                except Exception as e:  # noqa: BLE001 — a dying serve
                    # thread must FAIL the in-flight futures, not strand
                    # their callers on result() forever
                    self._fail_all(e)
                    raise
                if not worked:
                    self._stop_evt.wait(poll_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dl4j-serving-scheduler")
        self._thread.start()
        return self

    def _fail_all(self, exc: BaseException):
        """Resolve every queued and in-flight future with ``exc``, clear
        the pool, and leave a black box: a crash snapshot of the dying
        slot map + every doomed request's trace, dumped as JSONL (the
        serve-loop crash path). The futures fail FIRST — callers
        blocked in result() must not wait out the recording pass — and
        none of the recording may mask ``exc``."""
        with self._lock:
            slot_ids = [None if r is None else r.id for r in self.slots]
            queued_ids = [r.id for r in self._queue]
            doomed, seen = [], set()
            for r in list(self.slots) + list(self._queue):
                # a beam group occupies several lanes — fail it ONCE
                if r is not None and r.id not in seen:
                    seen.add(r.id)
                    doomed.append(r)
            self.slots = [None] * self.n_slots
            self._queue.clear()
            if self.paged:      # dead pool leaks no pages
                self._pages.reset()
                if self._prefix is not None:
                    # reset() zeroed the refcounts the cache's holds
                    # backed — drop the bookkeeping without decref
                    self._prefix.forget()
        for req in doomed:
            try:
                req.future.set_exception(exc)
            except InvalidStateError:
                pass
        err = repr(exc)[:300]
        try:
            m = self._m()
            self._steps += 1
            self.flight_recorder.record_snapshot(
                step=self._steps, crash=True, error=err, slots=slot_ids,
                queue=queued_ids, queue_depth=len(queued_ids),
                occupancy=sum(s is not None for s in slot_ids)
                / self.n_slots)
            for req in doomed:
                self._close_trace(req, "fail", m, error=err)
            self.flight_recorder.dump(reason="fail_all")
        except Exception:  # noqa: BLE001 — a failed postmortem (full
            pass           # disk, torn state) must not mask exc

    def stop(self):
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=30)
        self._thread = None

    def drain(self, max_steps: int = 100000) -> List["ServingRequest"]:
        """Graceful retire (ISSUE 18): stop admission, FINISH every
        request already occupying a slot (their futures resolve
        normally), then hand back the still-unstarted queue entries
        instead of failing them — the fleet router re-routes those to a
        surviving replica. Contrast ``_fail_all``, the crash path.

        Returned entries may include recompute-preemption victims whose
        futures are already RUNNING and whose ``generated`` is partial;
        re-running the ORIGINAL prompt elsewhere reproduces the same
        greedy output (prefill recomputes exactly the logits the
        interrupted decode would have seen), so the router resubmits
        ``req.prompt`` and resolves the caller from the fresh run.

        Safe to call while the background serve loop runs — the flag
        stops its admissions too and ``step()`` is ``_step_lock``-
        serialized; the scheduler accepts submits again after drain
        returns (the router usually discards it instead)."""
        with self._lock:
            self._draining = True
        try:
            for _ in range(max_steps):
                with self._lock:
                    busy = any(self.slots)
                if not busy:
                    break
                self.step()
            else:
                raise RuntimeError(
                    f"drain: pool not empty after {max_steps} steps")
            with self._lock:
                leftover = list(self._queue)
                self._queue.clear()
                self._m()["queue_depth"].set(0, replica=self.replica)
            return leftover
        finally:
            with self._lock:
                self._draining = False

    # ------------------------------------------------------ internals
    def _free_slots(self):
        return [i for i, r in enumerate(self.slots) if r is None]

    def _admission_plan(self, req):
        """Paged-admission plan for ``req`` (caller holds ``_lock``):
        ``(shared_pages, matched_tokens, need)`` — the resident pages
        its prompt prefix already has (ISSUE 16: session retention
        first, then the block index), the prompt tokens those cover,
        and the FREE pages its first prefill chunk still needs. The
        match is capped at ``ctx_len - 1`` so at least one token always
        prefills — the final chunk's logits are the first-token sample.
        Without the prefix cache this degenerates to the PR 14
        first-chunk page count."""
        ctx_len = req.prompt.size + len(req.generated)
        if self._prefix is None or req.kind in (RequestKind.SCORE,
                                                RequestKind.EMBED):
            # SCORE needs every position's logits and EMBED every
            # position's hidden row — a prefix hit would skip them
            return [], 0, self._pages.pages_for(
                min(ctx_len, self.engine.chunk_len))
        ctx = req.context()
        cap = ctx_len - 1
        shared: List[int] = []
        matched = 0
        if req.session_id is not None:
            sm = self._prefix.session_match(req.session_id, ctx)
            if sm is not None:
                n, shared = sm
                # identical resubmit: keep the pages (CoW rewrites the
                # tail position) but leave one token to prefill
                matched = min(n, cap)
        if not shared:
            shared = self._prefix.match(ctx)
            while shared and len(shared) * self._pages.page_len > cap:
                shared.pop()
            matched = len(shared) * self._pages.page_len
        first_end = min(ctx_len, matched + self.engine.chunk_len)
        need = max(0, self._pages.pages_for(first_end) - len(shared))
        return shared, matched, need

    def _head_first_chunk_pages(self) -> int:
        """FREE pages the queue head's first prefill chunk needs, net
        of any resident shared prefix (paged)."""
        return self._admission_plan(self._queue[0])[2]

    def _preempt_slot(self, victim_slot: int, m) -> "ServingRequest":
        """Preempt the request in ``victim_slot`` (caller holds
        ``_lock``): free the lane, return its pages to the pool, reset
        any mid-prefill progress, and re-queue its context at the BACK
        (recompute preemption). Shared by the starvation guard and the
        page-pressure path. A beam request (ISSUE 20) preempts as a
        GROUP — its lanes share pages and advance in lockstep, so
        evicting one would orphan the joint ranking; the rerun restarts
        from the prompt and, being greedy over exact log-probs,
        reproduces the same hypotheses. Partial SCORE/EMBED tallies
        reset too (re-admission re-prefills from position 0)."""
        victim = self.slots[victim_slot]
        if victim.beam is not None:
            for s in range(self.n_slots):
                if self.slots[s] is victim:
                    self.slots[s] = None
                    self._release_pages(s)
            victim.beam = BeamState(width=victim.beam_width)
            victim.released_pages = 0
        else:
            self.slots[victim_slot] = None
            self._release_pages(victim_slot)
        victim.score_lps = []
        victim.embed_acc = None
        victim.embed_last = None
        victim.pending = None
        victim.done_tokens = 0
        victim.preemptions += 1
        victim.queued_ts = time.perf_counter()
        if victim.trace is not None:
            victim.trace.event("preempt", ts=victim.queued_ts,
                               slot=victim_slot,
                               generated=len(victim.generated))
            victim.trace.event("requeue", ts=victim.queued_ts)
        self._queue.append(victim)
        m["preemptions"].inc()
        return victim

    def _release_pages(self, slot: int) -> int:
        """Paged mode: drop the slot's page holds (a no-op under dense
        slotting). Returns mappings removed; pages the prefix cache
        still holds stay resident (cached) rather than freeing."""
        return self._pages.release(slot) if self.paged else 0

    def _slot_pages(self, slot: int) -> List[int]:
        """The slot's mapped pool pages in logical order (paged mode,
        caller holds ``_lock``)."""
        return self._pages.slot_pages(slot)

    def _retire_slot(self, slot: int, req: "ServingRequest") -> int:
        """Finish-path page retirement (caller holds ``_lock``): with
        the prefix cache, REGISTER the request's written context before
        dropping the slot's holds — full blocks into the block index
        (cross-request sharing), and, for a ``session_id`` request, the
        whole written mapping (partial tail page included) under the
        session so the next turn resumes append-only. The last sampled
        token's k/v was never written, so the retained context stops
        one short. Preemption does NOT register (its whole point is to
        actually free pages — registration there would livelock the
        page-pressure path). Returns mappings removed."""
        if not self.paged:
            return 0
        if self._prefix is not None:
            ctx = req.context()
            written = int(ctx.size) - 1
            pages = self._slot_pages(slot)
            if written > 0 and pages:
                self._pages.note_fill(slot, written)
                self._prefix.insert(ctx[:written], pages)
                if req.session_id is not None:
                    keep = self._pages.pages_for(written)
                    self._prefix.retain_session(
                        req.session_id, ctx[:written], pages[:keep])
        return self._pages.release(slot)

    def _maybe_preempt(self, m) -> bool:
        """Starvation guard: queue head waited past the deadline and
        cannot admit — no free slot, or (paged) not enough free pages
        for its first chunk → preempt the active request with the most
        remaining budget (it blocks the pool longest). Its context
        re-queues at the BACK; the head admits into the freed
        lane/pages this same step."""
        if self.starvation_ms is None or not self._queue or self._draining:
            return False
        if self._free_slots() and not (
                self.paged
                and self._head_first_chunk_pages() > self._pages.free_pages):
            return False
        waited_ms = (time.perf_counter() - self._queue[0].queued_ts) * 1e3
        if waited_ms <= self.starvation_ms:
            return False
        # victims come from the DECODING slots only: a mid-chunked-
        # prefill slot always carries the pool's max remaining budget
        # (nothing generated yet), so including it would win every
        # max() and then fail the nothing-to-save guard — silently
        # disabling starvation relief for the whole multi-step
        # admission window chunked prefill creates
        victim_slot = max(
            (i for i, r in enumerate(self.slots)
             if r is not None and r.pending is None),
            key=lambda i: self.slots[i].remaining(), default=None)
        if victim_slot is None:
            return False
        victim = self.slots[victim_slot]
        progress = (victim.beam.progress() if victim.beam is not None
                    else len(victim.generated))
        if victim.remaining() <= 0 or not progress:
            return False       # nothing to save / about to finish anyway
        self._preempt_slot(victim_slot, m)
        return True

    def _pop_admissions(self, m):
        """Under the metadata lock: pair free slots with queued requests
        and RESERVE the slots (so occupancy readers see them) before the
        device-side prefills run lock-free. A request whose future was
        cancelled while queued is dropped here — it never costs a
        prefill. Paged mode gates admission on PAGE availability too
        (the head's first chunk must fit the free list) — the pool
        admits to actual token residency, not lane count. A BEAM head
        (ISSUE 20) reserves its WHOLE group — ``beam_width`` lanes — in
        one admission (the root lane prefills; the siblings stay empty
        until the fan-out) or waits: FIFO holds either way."""
        out = []
        if self._draining:      # drain(): queued entries stay queued —
            return out          # they are handed back, not admitted
        reserved = 0            # pages promised to this batch's heads
        while self._queue:
            req = self._queue[0]
            lanes = req.beam_width if req.kind is RequestKind.BEAM \
                else 1
            free = self._free_slots()
            if len(free) < lanes:
                break           # FIFO holds: the head cannot get lanes
            shared: List[int] = []
            matched = need = 0
            if self.paged:
                shared, matched, need = self._admission_plan(req)
                if need > self._pages.free_pages - reserved:
                    # LRU-evict cold cached prefix pages BEFORE
                    # refusing admission (ISSUE 16) — the pages the
                    # head just matched are protected until mapped
                    if self._prefix is not None:
                        freed = self._prefix.evict(
                            need - (self._pages.free_pages
                                    - reserved),
                            protect=frozenset(shared))
                        if freed:
                            m["kv_prefix_evictions"].inc(freed)
                    if need > self._pages.free_pages - reserved:
                        break   # FIFO holds: nothing admits past a
                                # head that cannot get pages
            self._queue.popleft()
            # fresh requests are PENDING → claim them (rejecting
            # cancelled ones); a re-queued preemption victim is
            # already RUNNING and must not be re-claimed
            if not req.future.running() and \
                    not req.future.set_running_or_notify_cancel():
                m["completions"].inc(reason="cancelled")
                self._close_trace(req, "cancel", m)
                continue
            slot = free[0]
            now = time.perf_counter()
            m["queue_wait"].observe(now - req.queued_ts)
            if req.trace is not None:
                req.trace.event("admit", ts=now, slot=slot)
            if self.paged:
                req.pending = req.context()
                req.done_tokens = 0
                req.prefill_s = 0.0
                req.chunks = 0
                req.prefix_matched = 0
                if shared:
                    # map the matched prefix NOW (same lock hold as
                    # the plan — eviction cannot slip between):
                    # those tokens never prefill, the tail chunks
                    # start past them
                    self._pages.map_shared(slot, shared)
                    self._pages.note_fill(slot, matched)
                    req.done_tokens = matched
                    req.prefix_matched = matched
                    self._prefix.note_hit(matched)
                    m["kv_prefix_hits"].inc()
                    m["kv_prefix_hit_tokens"].inc(matched)
                    if req.trace is not None:
                        req.trace.event(
                            "prefix_hit", ts=now,
                            matched_tokens=int(matched),
                            shared_pages=len(shared))
                reserved += need
            if req.kind is RequestKind.BEAM:
                # group reservation: every lane points at the one
                # request; only the root (slots[0]) prefills
                req.beam = BeamState(width=lanes,
                                     slots=list(free[:lanes]))
                req.released_pages = 0
                for s in free[:lanes]:
                    self.slots[s] = req
            else:
                self.slots[slot] = req        # reserve
            out.append((slot, req))
        return out

    def _admit_one(self, slot, req, m):
        """Device-side admission for one reserved slot (dense mode):
        prefill the request's whole context, sample its first token
        (TTFT). Runs outside the metadata lock — `_step_lock` already
        serializes cache use."""
        ctx = req.context()
        t0 = time.perf_counter()
        with span("serving.prefill",
                  attrs={"request": req.id, "slot": slot,
                         "tokens": int(ctx.size)}):
            logits, self.cache = self.engine.prefill_slot(
                self.cache, ctx, slot)
        self._first_token(slot, req, logits, int(ctx.size),
                          time.perf_counter() - t0, m)

    def _advance_prefills(self, m) -> bool:
        """Paged mode: advance every prefilling slot by ONE chunk (the
        ISSUE 14 interleave — the decode sweep that follows never waits
        out more than ``engine.chunk_len`` prompt tokens). Pages for
        the chunk are mapped first; under page pressure the biggest-
        remaining active neighbour is preempted, and if the pool STILL
        cannot cover the chunk the prefilling request itself re-queues
        (its turn comes back when pages free). The final chunk ends the
        prefill phase per kind (ISSUE 20): GENERATE/CONSTRAINED sample
        their first token (TTFT), SCORE/EMBED retire on the spot
        (prefill IS the product), BEAM fans out into its group. A beam
        group's sibling lanes never prefill — only the root works
        here."""
        with self._lock:
            work = [(i, r) for i, r in enumerate(self.slots)
                    if r is not None and r.pending is not None
                    and (r.beam is None
                         or (r.beam.slots and i == r.beam.slots[0]))]
        did = False
        for slot, req in work:
            with self._lock:
                if self.slots[slot] is not req:   # preempted meanwhile
                    continue
                ctx = req.pending
                done = req.done_tokens
                n = min(self.engine.chunk_len, len(ctx) - done)
                ok = self._ensure_pages(slot, req, done + n, m)
                # CoW (ISSUE 16): pages this chunk writes into that
                # have other holders split first — planned under the
                # lock, copied on device outside it
                cows = self._plan_cow(slot, done, done + n, m) \
                    if ok and self.slots[slot] is req else []
                ok = ok and self.slots[slot] is req
            if not ok:
                did = True      # a preemption shuffle IS work
                continue
            did = True
            for src, dst in cows:
                self.cache = self.engine.copy_page(self.cache, src, dst)
            self.cache = self._pages.sync(self.cache)
            t0 = time.perf_counter()
            rows = logits = None
            if req.kind is RequestKind.SCORE:
                # verify_chunk returns EVERY row's logits (with the
                # decode-side params, so quantized serving scores with
                # the weights it decodes with)
                with span("serving.score_chunk",
                          attrs={"request": req.id, "slot": slot,
                                 "start": int(done), "tokens": int(n)}):
                    rows, self.cache = self.engine.verify_chunk(
                        self.cache, ctx[done:done + n], slot,
                        start=done)
            elif req.kind is RequestKind.EMBED:
                with span("serving.embed_chunk",
                          attrs={"request": req.id, "slot": slot,
                                 "start": int(done), "tokens": int(n)}):
                    rows, self.cache = self.engine.embed_chunk(
                        self.cache, ctx[done:done + n], slot,
                        start=done)
            else:
                with span("serving.prefill_chunk",
                          attrs={"request": req.id, "slot": slot,
                                 "start": int(done), "tokens": int(n)}):
                    logits, self.cache = self.engine.prefill_chunk(
                        self.cache, ctx[done:done + n], slot,
                        start=done)
            elapsed = time.perf_counter() - t0
            if req.kind is RequestKind.SCORE:
                self._score_rows(req, ctx, done, n, rows)
            elif req.kind is RequestKind.EMBED:
                self._embed_rows(req, n, rows)
            with self._lock:
                req.prefill_s += elapsed
                req.chunks += 1
                req.done_tokens = done + n
                final = req.done_tokens >= len(ctx)
                if final:
                    req.pending = None
            if final:
                if req.kind in (RequestKind.SCORE, RequestKind.EMBED):
                    self._finish_prefill_only(slot, req, m)
                elif req.beam is not None:
                    self._expand_beam(slot, req, logits, len(ctx),
                                      req.prefill_s, m)
                else:
                    self._first_token(slot, req, logits, len(ctx),
                                      req.prefill_s, m,
                                      chunks=req.chunks)
        return did

    @staticmethod
    def _score_rows(req, ctx, done: int, n: int, rows):
        """Fold one verify chunk's row logits into the running SCORE
        tally: row i (global position ``done+i``) is the next-token
        distribution after ``ctx[:done+i+1]``, so it scores
        ``ctx[done+i+1]`` — the context's final row has no target and
        is dropped. Host-side f32 log-softmax (one pass per chunk)."""
        tgt = np.asarray(ctx[done + 1: done + n + 1], np.int64)
        if not tgt.size:
            return
        lg = np.asarray(rows, np.float32)[:tgt.size]
        mx = lg.max(axis=-1, keepdims=True)
        lse = mx[:, 0] + np.log(np.exp(lg - mx).sum(axis=-1))
        req.score_lps.extend(
            (lg[np.arange(tgt.size), tgt] - lse).tolist())

    @staticmethod
    def _embed_rows(req, n: int, rows):
        """Fold one embed chunk's hidden rows into the pooling
        accumulators: a running sum for "mean", the newest valid row
        for "last" (rows past ``n`` are bucket padding)."""
        hid = np.asarray(rows, np.float32)[:n]
        s = hid.sum(axis=0)
        req.embed_acc = s if req.embed_acc is None else req.embed_acc + s
        req.embed_last = hid[-1]

    def _ensure_pages(self, slot, req, tokens: int, m) -> bool:
        """Grow ``slot``'s mapping to cover ``tokens`` rows, preempting
        under page pressure (caller holds ``_lock``). Victim order:
        DECODING slots first, by most remaining budget — they block the
        pool longest and a recompute costs them one prefill; a
        mid-chunked-prefill slot is only sacrificed when no decoding
        victim frees enough, least-progress first — discarding a
        nearly-done long prefill for one page of decode growth would
        re-pay every chunk AND invite the same squeeze on re-admission
        (livelock by thrash). If the pool still cannot cover the
        growth, ``req`` itself is preempted (False: the lane is free,
        the request re-queued — never stranded, the submit-time fit
        check guarantees it runs once pages free up).

        With the prefix cache (ISSUE 16), LRU eviction of cold cached
        pages runs BEFORE the preemption cascade and again after each
        preemption (a victim's release may leave its registered pages
        cached rather than free)."""
        if self._try_map(slot, tokens, m):
            return True
        while True:
            # a beam sibling (same request, different lane) is never a
            # victim here — preempting it would preempt the WHOLE
            # group, ``slot`` included (ISSUE 20)
            victim_slot = max(
                (i for i, r in enumerate(self.slots)
                 if r is not None and i != slot and r is not req),
                key=lambda i: (self.slots[i].pending is None,
                               -self.slots[i].done_tokens
                               if self.slots[i].pending is not None
                               else self.slots[i].remaining()),
                default=None)
            if victim_slot is None:
                break
            self._preempt_slot(victim_slot, m)
            if self._try_map(slot, tokens, m):
                return True
        self._preempt_slot(slot, m)
        return False

    def _try_map(self, slot, req_or_slot_tokens, m=None) -> bool:
        """``PageTable.map`` with the ISSUE 16 eviction step: when the
        free list cannot cover the growth, LRU-evict cached prefix
        pages (cold cache beats preempting live requests) and retry
        once. Caller holds ``_lock``."""
        tokens = int(req_or_slot_tokens)
        if self._pages.map(slot, tokens):
            return True
        if self._prefix is not None:
            short = (self._pages.pages_for(tokens)
                     - int(self._pages.mapped[slot])
                     - self._pages.free_pages)
            if short > 0:
                freed = self._prefix.evict(short)
                if freed and m is not None:
                    m["kv_prefix_evictions"].inc(freed)
                if freed and self._pages.map(slot, tokens):
                    return True
        return False

    def _plan_cow(self, slot, start: int, end: int, m) -> list:
        """Split every page ``slot`` is about to write (context rows
        ``[start, end)``) that has other holders (ISSUE 16 CoW). Caller
        holds ``_lock``; returns the ``(src, dst)`` pool-page copies
        the caller must run on device (``engine.copy_page``) BEFORE the
        write dispatch — device work never runs under the lock.

        Starvation ladder when no free page exists for the split:
        evict cold cache, then transfer sole ownership (drop the cache
        holds on the contested page — the write is then private, no
        copy needed), then preempt the other slot mapping it.

        Runs whenever the pool is paged — beam groups (ISSUE 20) share
        pages WITHOUT the prefix cache, so the split logic cannot hide
        behind it; the cache-only ladder rungs are skipped when there
        is no cache."""
        if not self.paged or end <= start:
            return []
        plen = self._pages.page_len
        copies = []
        for j in range(start // plen, (end - 1) // plen + 1):
            if j >= int(self._pages.mapped[slot]):
                break
            while True:
                p = int(self._pages.table[slot, j])
                if int(self._pages.refcount[p]) <= 1:
                    break                      # private: write in place
                split = self._pages.cow(slot, j)
                if split is not None:
                    copies.append(split)
                    if self._prefix is not None:
                        self._prefix.cow_copies += 1
                    m["kv_cow"].inc()
                    break
                # no free page for the copy: reclaim, cheapest first
                if self._prefix is not None:
                    freed = self._prefix.evict(1)
                    if freed:
                        m["kv_prefix_evictions"].inc(freed)
                        continue
                    if self._prefix.release_page_holds(p):
                        continue               # may now be private
                other = next(
                    (i for i in range(self.n_slots)
                     if i != slot and self.slots[i] is not None
                     and p in self._pages.table[
                         i, :int(self._pages.mapped[i])]),
                    None)
                if other is None:              # cannot happen: refs
                    break                      # must come from somewhere
                self._preempt_slot(other, m)
                if self.slots[slot] is None:
                    # ``other`` was a beam sibling: the group preempt
                    # took this slot down with it — nothing to plan
                    return copies
        return copies

    def _first_token(self, slot, req, logits, ctx_tokens: int,
                     prefill_s: float, m, chunks: Optional[int] = None):
        """Shared admission tail (dense prefill_slot and the final
        prefill chunk): sample the first token — the TTFT sample —
        record the trace events, and either park the token for the next
        sweep or finish immediately (budget 1 / instant eos)."""
        m["prefills"].inc()
        with self._lock:
            self._key, sub = jax.random.split(self._key)
        if req.kind is RequestKind.CONSTRAINED:
            # the pre-warmed masked sampler (ISSUE 20) — an all-true
            # mask is bit-identical to the plain path
            mask = workloads.resolve_mask(
                req.token_mask, req.generated,
                int(self.engine.cfg.vocab_size))
            tok = int(np.asarray(self.engine.sample_masked(
                sub, logits[None], req.temperature, req.top_k,
                mask[None]))[0])
        else:
            tok = int(np.asarray(self.engine.sample(
                sub, logits[None], req.temperature, req.top_k))[0])
        # the TTFT timestamp is taken BEFORE the sampler-obs pass: its
        # cost is booked to trace_overhead, so it must not also ride
        # the recorded first-token latency (no double counting)
        now = time.perf_counter()
        # sampler obs (ISSUE 13) on the first (TTFT) token
        obs_cost = self._maybe_sample_obs(m, lambda: np.asarray(logits),
                                          [req.top_k])
        with self._lock:
            self._trace_overhead += obs_cost
            if req.first_token_ts is None:
                req.first_token_ts = now
                m["ttft"].observe(now - req.submitted_ts)
            if req.trace is not None:
                t_ov = time.perf_counter()
                attrs = {} if chunks is None else {"chunks": chunks}
                req.trace.event("prefill", ts=now, slot=slot,
                                tokens=ctx_tokens, time_s=prefill_s,
                                **attrs)
                req.trace.event("token", ts=now, i=len(req.generated))
                self._trace_overhead += time.perf_counter() - t_ov
            if self.paged and self._prefix is not None:
                # register the just-prefilled context's full blocks so
                # CONCURRENT requests with the same prompt share them
                # from their own admission onward (finish re-registers
                # the generated extension)
                ctx_now = req.context()
                self._pages.note_fill(slot, ctx_now.size)
                self._prefix.insert(
                    ctx_now, self._slot_pages(slot))
            req.generated.append(tok)
            m["tokens"].inc()
            m["wl_tokens"].inc(kind=req.kind.value)
            if self._done(req, tok):
                self.slots[slot] = None
                released = self._retire_slot(slot, req)
                self._finish(req, tok, m, mapped_pages=released)
            else:
                self._last_tokens[slot] = tok

    def _maybe_sample_obs(self, m, rows_fn, topks) -> float:
        """Shared sampler-obs cadence for admissions and sweeps (one
        counter, one modulo, one timing discipline): returns the
        self-timed cost to add to trace_overhead. ``rows_fn`` defers
        the logits fetch until the cadence says observe — runs under
        ``_step_lock`` only, like its two callers."""
        if not self.sample_obs_every:
            return 0.0
        self._obs_events += 1
        if self._obs_events % self.sample_obs_every:
            return 0.0
        t_obs = time.perf_counter()
        try:
            self._sample_obs(m, rows_fn(), topks)
        except Exception:  # noqa: BLE001 — observability must never
            pass           # perturb the admission or sweep
        return time.perf_counter() - t_obs

    @staticmethod
    def _sample_obs(m, logits_rows, topks):
        """Sampler observability (ISSUE 13), host-side only: mean
        next-token entropy over the given logit rows, and the mean
        probability mass the top-k filter keeps for rows with
        top_k > 0. No device computation — one fetch of logits the
        sampler produced anyway; f32 + in-place numpy + partition
        (not sort) keep an observation in the tens of microseconds."""
        lg = np.array(logits_rows, np.float32, copy=True)
        if lg.ndim == 1:
            lg = lg[None, :]
        if lg.size == 0:
            return
        lg -= lg.max(axis=-1, keepdims=True)
        np.exp(lg, out=lg)
        lg /= lg.sum(axis=-1, keepdims=True)        # lg is now p
        ent = -(lg * np.log(lg + 1e-30)).sum(axis=-1)
        m["sample_entropy"].observe(float(ent.mean()))
        mass, n_k = 0.0, 0
        for row, k in zip(lg, topks):
            k = int(k)
            if k <= 0:
                continue
            k = min(k, row.size)
            mass += float(np.partition(row, row.size - k)
                          [row.size - k:].sum())
            n_k += 1
        if n_k:
            m["topk_mass"].observe(mass / n_k)

    def _decode_sweep(self, m) -> bool:
        with self._lock:      # snapshot; only step() (serialized) mutates
            if self.paged:
                # page growth BEFORE the sweep: each decoding slot's
                # next write position must be mapped (a data update,
                # never a retrace — the gather shape is fixed). Under
                # pressure _ensure_pages preempts, so re-derive the
                # active set afterwards.
                cows = []
                for i in range(self.n_slots):
                    req = self.slots[i]
                    if req is None or req.pending is not None:
                        continue
                    w = self._slot_tokens(req)
                    ok = self._ensure_pages(i, req, w, m)
                    if ok and self.slots[i] is req:
                        # the sweep writes this slot's row w-1: split
                        # it first if shared (ISSUE 16 session appends,
                        # ISSUE 20 beam siblings on one tail page)
                        cows.extend(self._plan_cow(i, w - 1, w, m))
            else:
                cows = []
            active = [i for i, r in enumerate(self.slots)
                      if r is not None and r.pending is None]
            if not active:
                return False
            vocab = int(self.engine.cfg.vocab_size)
            temps = np.zeros((self.n_slots,), np.float32)
            topks = np.zeros((self.n_slots,), np.int32)
            masks = None
            for i in active:
                temps[i] = self.slots[i].temperature
                topks[i] = self.slots[i].top_k
                if self.slots[i].kind is RequestKind.CONSTRAINED:
                    # grammar step (ISSUE 20): consult the mask for the
                    # NEXT token; unconstrained lanes stay all-true —
                    # bit-identical to the plain sampler
                    if masks is None:
                        masks = np.ones((self.n_slots, vocab), bool)
                    masks[i] = workloads.resolve_mask(
                        self.slots[i].token_mask,
                        self.slots[i].generated, vocab)
            active_kinds = [self.slots[i].kind.value for i in active]
            tokens_in = jnp.asarray(self._last_tokens)
            self._key, sub = jax.random.split(self._key)
        if self.paged:
            for src, dst in cows:
                self.cache = self.engine.copy_page(self.cache, src, dst)
            self.cache = self._pages.sync(self.cache)
        t0 = time.perf_counter()
        with span("serving.decode", attrs={"active": len(active)}):
            logits, self.cache = self.engine.decode_step(
                self.cache, tokens_in)
            if masks is None:
                toks = np.asarray(self.engine.sample(sub, logits, temps,
                                                     topks))
            else:
                toks = np.asarray(self.engine.sample_masked(
                    sub, logits, temps, topks, masks))
        dt = time.perf_counter() - t0
        m["decode_steps"].inc()
        m["decode_s"].observe(dt)
        m["occupancy"].set(len(active) / self.n_slots,
                           replica=self.replica)
        m["tokens"].inc(len(active))
        for kv in set(active_kinds):
            m["wl_tokens"].inc(active_kinds.count(kv), kind=kv)
        if dt > 0:
            m["tokens_per_s"].set(len(active) / dt, replica=self.replica)
        # token timestamp BEFORE the sampler-obs pass: its cost is
        # booked to trace_overhead, so it must not also skew the ITL
        # samples derived from consecutive token events (the same
        # no-double-counting discipline as _admit's TTFT timestamp)
        tok_ts = time.perf_counter()
        obs_cost = self._maybe_sample_obs(
            m, lambda: np.asarray(logits)[active],
            [topks[i] for i in active])
        with self._lock:
            # trace bookkeeping first (self-timed): one shared token
            # timestamp per sweep — the whole pool's tokens land
            # together, which is exactly what each caller observes
            self._trace_overhead += obs_cost   # sampler obs (ISSUE 13)
            t_ov = time.perf_counter()
            for i in active:
                req = self.slots[i]
                if req is not None and req.beam is None \
                        and req.trace is not None:
                    req.trace.event("token", ts=tok_ts,
                                    i=len(req.generated))
            self._trace_overhead += time.perf_counter() - t_ov
            beams = []
            for i in active:
                req = self.slots[i]
                if req is None:
                    continue
                if req.beam is not None:
                    # joint advance once per GROUP, below — a per-lane
                    # independent sample would break the beam ranking
                    if all(b is not req for b in beams):
                        beams.append(req)
                    continue
                tok = int(toks[i])
                req.generated.append(tok)
                self._last_tokens[i] = tok
                if self._done(req, tok):
                    self.slots[i] = None
                    released = self._retire_slot(i, req)
                    self._finish(req, tok, m, mapped_pages=released)
            if beams:
                logits_np = np.asarray(logits, np.float32)
                for req in beams:
                    self._advance_beam(req, logits_np, m, tok_ts)
        return True

    @staticmethod
    def _done(req: ServingRequest, tok: int) -> bool:
        return (req.eos_id is not None and tok == req.eos_id) \
            or len(req.generated) >= req.max_new_tokens

    @staticmethod
    def _slot_tokens(r: ServingRequest) -> int:
        """Tokens a slot holding ``r`` accounts for: chunk progress
        while prefilling, prompt + generated when decoding — with the
        beam group's lockstep progress standing in for ``generated``
        on its lanes (ISSUE 20)."""
        if r.pending is not None:
            return r.done_tokens
        if r.beam is not None:
            return r.prompt.size + r.beam.progress()
        return r.prompt.size + len(r.generated)

    # ------------------------------------------------ beam search (20)
    def _expand_beam(self, root: int, req: ServingRequest, logits,
                     ctx_tokens: int, prefill_s: float, m):
        """Fan the finished root prefill out into the beam group: rank
        the root's next-token log-probs, give the top-k candidates one
        reserved lane each — the root keeps its lane in place, every
        sibling ``map_shared``s the root's pages, so the whole prefix
        costs ONE set of pages and divergence splits lazily through the
        sweep's CoW pass. This is the TTFT sample. A candidate that is
        terminal on arrival (instant EOS / budget 1) goes straight to
        the done list and frees its lane."""
        m["prefills"].inc()
        lg = np.asarray(logits, np.float32)
        lg = lg - lg.max()
        lsm = lg - np.log(np.exp(lg).sum())
        now = time.perf_counter()
        pos_fix = []
        with self._lock:
            beam = req.beam
            if beam is None or self.slots[root] is not req:
                return          # group preempted since the last chunk
            if req.first_token_ts is None:
                req.first_token_ts = now
                m["ttft"].observe(now - req.submitted_ts)
            if req.trace is not None:
                t_ov = time.perf_counter()
                req.trace.event("prefill", ts=now, slot=root,
                                tokens=ctx_tokens, time_s=prefill_s,
                                chunks=req.chunks)
                req.trace.event("token", ts=now, i=0)
                self._trace_overhead += time.perf_counter() - t_ov
            lanes = list(beam.slots)
            order = np.argsort(-lsm, kind="stable")[:len(lanes)]
            root_pages = self._pages.slot_pages(root)
            self._pages.note_fill(root, ctx_tokens)
            alive_slots: List[int] = []
            alive_tokens: List[List[int]] = []
            alive_scores: List[float] = []
            root_done = False
            for rank, t in enumerate(order):
                t, sc = int(t), float(lsm[int(t)])
                slot = lanes[rank]
                finished = ((req.eos_id is not None
                             and t == req.eos_id)
                            or req.max_new_tokens <= 1)
                if rank > 0 and not finished:
                    # the fan-out itself costs ZERO new pages
                    self._pages.map_shared(slot, root_pages)
                    self._pages.note_fill(slot, ctx_tokens)
                    pos_fix.append(slot)
                if finished:
                    beam.done.append(([t], sc))
                    if rank == 0:
                        root_done = True    # release AFTER clones map
                    else:
                        self.slots[slot] = None
                else:
                    alive_slots.append(slot)
                    alive_tokens.append([t])
                    alive_scores.append(sc)
                    self._last_tokens[slot] = t
            for slot in lanes[len(order):]:   # vocab < width leftovers
                self.slots[slot] = None
            if root_done:
                req.released_pages += self._pages.release(root)
                self.slots[root] = None
            beam.slots, beam.tokens, beam.scores = \
                alive_slots, alive_tokens, alive_scores
            beam.expanded = True
            m["tokens"].inc(len(order))
            m["wl_tokens"].inc(len(order), kind=req.kind.value)
            if not alive_slots:
                self._finish_beam(req, m)
        if pos_fix:
            # sibling lanes were never prefilled — their cache position
            # must read the shared context length before the next
            # sweep (a data update on a fixed-shape array, no retrace)
            pos = np.array(self.cache["pos"])
            pos[np.asarray(pos_fix)] = ctx_tokens
            self.cache = dict(self.cache, pos=jnp.asarray(pos))

    def _advance_beam(self, req: ServingRequest, logits_np, m, tok_ts):
        """One joint beam step after the pool sweep (caller holds
        ``_lock``): rank score+logprob over every (live beam, token)
        pair, keep the top ``len(slots)``, and re-point the lanes — a
        parent's FIRST surviving candidate keeps the parent's lane
        (and pages) in place; every further candidate of the same
        parent re-maps a freed lane onto the parent's pages
        (``map_shared``; the next sweep's CoW pass splits the written
        tail page on divergence). EOS candidates retire to the done
        list and shrink the width. With width 1 the single candidate
        is ``argmax(logits)`` — bit-identical to greedy ``generate``."""
        beam = req.beam
        if beam is None or not beam.slots:
            return
        lanes = list(beam.slots)
        ka = len(lanes)
        lg = logits_np[np.asarray(lanes)]
        lg = lg - lg.max(axis=-1, keepdims=True)
        lsm = lg - np.log(np.exp(lg).sum(axis=-1, keepdims=True))
        vocab = lsm.shape[-1]
        cand = np.asarray(beam.scores, np.float64)[:, None] + lsm
        order = np.argsort(-cand, axis=None, kind="stable")[:ka]
        parents = (order // vocab).astype(int)
        toks = (order % vocab).astype(int)
        if req.trace is not None:
            t_ov = time.perf_counter()
            req.trace.event("token", ts=tok_ts, i=beam.progress())
            self._trace_overhead += time.perf_counter() - t_ov
        written = req.prompt.size + len(beam.tokens[0])
        # page lists snapshot BEFORE any release — a clone increfs its
        # parent's pages from this list
        parent_pages = {int(p): self._pages.slot_pages(lanes[int(p)])
                        for p in set(parents.tolist())}
        chosen = set(parents.tolist())
        # lanes of parents with NO surviving candidate free first —
        # clones re-map onto them (nobody clones FROM them, so the
        # release is safe); a selected parent's pages release only
        # after every clone has incref'd them
        free_lanes = [lanes[p] for p in range(ka) if p not in chosen]
        for s in free_lanes:
            req.released_pages += self._pages.release(s)
        alive_slots: List[int] = []
        alive_tokens: List[List[int]] = []
        alive_scores: List[float] = []
        deferred: List[int] = []
        first_seen: set = set()
        for r in range(len(order)):
            p, t = int(parents[r]), int(toks[r])
            sc = float(cand[p, t])
            seq = beam.tokens[p] + [t]
            finished = ((req.eos_id is not None and t == req.eos_id)
                        or len(seq) >= req.max_new_tokens)
            keeps_lane = p not in first_seen
            first_seen.add(p)
            if finished:
                beam.done.append((seq, sc))
                if keeps_lane:
                    deferred.append(lanes[p])
                continue
            if keeps_lane:
                slot = lanes[p]
            else:
                slot = free_lanes.pop()
                self._pages.map_shared(slot, parent_pages[p])
                self._pages.note_fill(slot, written)
                self.slots[slot] = req
            self._last_tokens[slot] = t
            alive_slots.append(slot)
            alive_tokens.append(seq)
            alive_scores.append(sc)
        for s in deferred:
            # parents whose lane-keeping candidate finished: release
            # only now — later-ranked clones of the same parent have
            # already incref'd the pages
            req.released_pages += self._pages.release(s)
            self.slots[s] = None
        for s in free_lanes:    # unselected lanes no clone claimed
            self.slots[s] = None
        beam.slots, beam.tokens, beam.scores = \
            alive_slots, alive_tokens, alive_scores
        if not alive_slots:
            self._finish_beam(req, m)

    # ----------------------------------------- typed finishes (20)
    def _finish_prefill_only(self, slot: int, req: ServingRequest, m):
        """SCORE/EMBED retire at their final prefill chunk — they never
        occupy decode-sweep time. The completion instant doubles as the
        first-token sample (the prefill IS the product, so TTFT ==
        latency) and the per-kind token counter books the prompt."""
        m["prefills"].inc()
        now = time.perf_counter()
        with self._lock:
            if self.slots[slot] is not req:
                return          # preempted between chunk and finish
            if req.first_token_ts is None:
                req.first_token_ts = now
                m["ttft"].observe(now - req.submitted_ts)
            if req.trace is not None:
                t_ov = time.perf_counter()
                req.trace.event("prefill", ts=now, slot=slot,
                                tokens=int(req.prompt.size),
                                time_s=req.prefill_s, chunks=req.chunks)
                req.trace.event("token", ts=now, i=0)
                self._trace_overhead += time.perf_counter() - t_ov
            self.slots[slot] = None
            released = self._release_pages(slot)
            n_tok = int(req.prompt.size)
            if req.kind is RequestKind.SCORE:
                lps = np.asarray(req.score_lps, np.float32)
                ppl = (float(np.exp(-lps.mean())) if lps.size
                       else float("inf"))
                result = ScoreResult(logprobs=lps, perplexity=ppl,
                                     prompt_tokens=n_tok)
            else:
                emb = (req.embed_last if req.pooling == "last"
                       else req.embed_acc / float(n_tok))
                result = EmbedResult(
                    embedding=np.asarray(emb, np.float32),
                    pooling=req.pooling, prompt_tokens=n_tok)
            m["wl_tokens"].inc(n_tok, kind=req.kind.value)
            self._finish_workload(req, result, "complete", m,
                                  released, n_tok)

    def _finish_beam(self, req: ServingRequest, m):
        """All hypotheses done (caller holds ``_lock``; lanes and pages
        were released as each one finished): resolve the future with
        the rank-sorted :class:`BeamResult`."""
        done = req.beam.done
        order = sorted(range(len(done)), key=lambda i: -done[i][1])
        seqs = [np.asarray(done[i][0], np.int32) for i in order]
        scores = [float(done[i][1]) for i in order]
        reason = ("eos" if (req.eos_id is not None and seqs
                            and seqs[0].size
                            and int(seqs[0][-1]) == req.eos_id)
                  else "length")
        result = BeamResult(sequences=seqs, scores=scores,
                            beam_width=req.beam_width,
                            finish_reason=reason)
        resident = req.prompt.size + (seqs[0].size if seqs else 0)
        self._finish_workload(req, result, reason, m,
                              req.released_pages, resident)

    def _finish_workload(self, req: ServingRequest, result, reason, m,
                         mapped_pages: int, resident: int):
        """Shared completion tail for the typed results (SCORE / EMBED
        / BEAM): latency + residency accounting, trace close-out, and
        the future resolution — the same discipline as the generation
        ``_finish`` with the result object swapped."""
        now = time.perf_counter()
        m["completions"].inc(reason=reason)
        m["wl_completions"].inc(kind=req.kind.value)
        m["latency"].observe(now - req.submitted_ts)
        result.latency_s = now - req.submitted_ts
        result.ttft_s = (None if req.first_token_ts is None
                         else req.first_token_ts - req.submitted_ts)
        result.prefill_s = req.prefill_s
        t_ov = time.perf_counter()
        resident = min(int(resident), self.engine.max_len)
        if self.paged:
            cap = max(1, mapped_pages) * self._pages.page_len
            ratio = min(1.0, resident / cap)
        else:
            ratio = resident / self.engine.max_len
        m["kv_final"].observe(ratio)
        self._final_res_sum += ratio
        self._final_res_n += 1
        self._close_trace(req, "finish", m, reason=reason,
                          resident_tokens=int(resident),
                          residency_ratio=round(ratio, 6))
        self._trace_overhead += time.perf_counter() - t_ov
        try:
            req.future.set_result(result)
        except InvalidStateError:
            pass   # the caller gave up on an in-flight request

    def _finish(self, req: ServingRequest, last_tok: int, m,
                mapped_pages: int = 0):
        reason = "eos" if (req.eos_id is not None
                           and last_tok == req.eos_id) else "length"
        now = time.perf_counter()
        m["completions"].inc(reason=reason)
        m["wl_completions"].inc(kind=req.kind.value)
        m["latency"].observe(now - req.submitted_ts)
        t_ov = time.perf_counter()
        # per-request final residency (ISSUE 12/14): how much of what
        # it RESERVED this request ever used — the fixed max_len slot
        # under dense slotting, its mapped pages under paging (where
        # the only reservable waste is the last page's tail)
        resident = min(req.prompt.size + len(req.generated),
                       self.engine.max_len)
        if self.paged:
            cap = max(1, mapped_pages) * self._pages.page_len
            ratio = min(1.0, resident / cap)
        else:
            ratio = resident / self.engine.max_len
        m["kv_final"].observe(ratio)
        self._final_res_sum += ratio
        self._final_res_n += 1
        self._close_trace(req, "finish", m, reason=reason,
                          resident_tokens=int(resident),
                          residency_ratio=round(ratio, 6))
        self._trace_overhead += time.perf_counter() - t_ov
        try:
            req.future.set_result(GenerationResult(
                tokens=np.asarray(req.generated, np.int32),
                finish_reason=reason, request_id=req.id,
                ttft_s=(None if req.first_token_ts is None
                        else req.first_token_ts - req.submitted_ts),
                latency_s=now - req.submitted_ts,
                preemptions=req.preemptions))
        except InvalidStateError:
            pass   # the caller gave up on an in-flight request; the
            # pool must keep serving its neighbours regardless

    def _close_trace(self, req: ServingRequest, kind: str, m, **attrs):
        """Terminal trace bookkeeping for one request: terminal event,
        per-request ITL samples into the histogram, black-box record,
        span-tree assembly, SLO accounting."""
        tr = req.trace
        if tr is None:
            return
        tr.event(kind, **attrs)
        summary = tr.summary()    # computed once: histogram + SLO share
        m["itl"].observe_many(summary["itl_s"])
        self.flight_recorder.record_request(tr)
        if self.slo is not None:
            self.slo.observe_summary(summary)
        if self.trace_spans:
            tr.assemble_spans()

    def _record_snapshot(self, m=None, **extra):
        """One flight-recorder snapshot of the scheduler state (called
        per working step, under ``_step_lock``). Carries the KV
        residency accounting (ISSUE 12) so the flight recorder IS the
        memory timeline: allocated vs resident bytes per step ride the
        same black box the crash dump and ``mem_report.py`` read.
        ``m`` is the caller's already-fetched metric map — re-fetching
        per snapshot would pay ~16 registry lookups per step, the
        single biggest avoidable cost against the <2% budget."""
        with self._lock:
            # ONE pass over the slots for ids + active count + kind
            # census + residency (this runs per step inside the
            # self-timed <2% bookkeeping budget; four separate
            # comprehensions measurably blew it). Census notes (ISSUE
            # 20): a beam group's lanes count its request ONCE (same
            # id); keyed by the enum member (identity hash) and
            # converted once at the end — Enum ``.value`` routes
            # through a DynamicClassAttribute descriptor, too slow for
            # a per-slot-per-step access. A mid-prefill slot is
            # resident only to the tokens its chunks actually wrote; a
            # beam lane's lockstep group progress stands in for
            # ``generated``.
            queued_ids = [r.id for r in self._queue]
            max_len = self.engine.max_len
            slot_ids: list = []
            kinds_e: dict = {}
            seen_ids: set = set()
            resident_tokens = 0
            n_active = 0
            for r in self.slots:
                if r is None:
                    slot_ids.append(None)
                    continue
                slot_ids.append(r.id)
                n_active += 1
                if r.id not in seen_ids:
                    seen_ids.add(r.id)
                    kinds_e[r.kind] = kinds_e.get(r.kind, 0) + 1
                if r.pending is not None:
                    t = r.done_tokens
                elif r.beam is not None:
                    t = r.prompt.size + r.beam.progress()
                else:
                    t = r.prompt.size + len(r.generated)
                resident_tokens += t if t < max_len else max_len
            kinds = {k.value: v for k, v in kinds_e.items()}
            # accumulators update under the cheap metadata lock — the
            # lock kv_report/reset_kv_window also take — so a reader
            # never sees a sum without its count, and never waits on
            # device work to see either
            resident = resident_tokens * self._kv_token_bytes
            if n_active > self._peak_active:
                self._peak_active = n_active
            if self.paged and self._prefix is not None:
                # CoW sharing (ISSUE 16): a shared page must count ONCE
                # — per-slot token sums would bill the same bytes to
                # every slot mapping them. Allocated = pool pages with
                # ≥1 holder (slots OR cache); resident = the per-page
                # fill census, refreshed here for the active slots
                # (cached pages keep the fill they retired with).
                for i, r in enumerate(self.slots):
                    if r is not None:
                        self._pages.note_fill(
                            i, self._slot_tokens(r)
                            - (0 if r.pending is not None else 1))
                alloc = self._pages.used_pages * self._kv_page_bytes
                mapped = self._pages.mapped_pages
                resident = min(self._pages.resident_tokens
                               * self._kv_token_bytes, alloc)
            elif self.paged:
                # page granularity (ISSUE 14): allocated = MAPPED pages,
                # not the pool — waste is unfilled page tails only. A
                # just-sampled token is counted resident one sweep before
                # its k/v rows are written (the next sweep's
                # _ensure_pages maps its page first), so at an exact
                # page boundary resident can momentarily exceed the
                # mapping — clamp, or the waste gauge reads negative
                alloc = self._pages.mapped_pages * self._kv_page_bytes
                mapped = self._pages.mapped_pages
                resident = min(resident, alloc)
            else:
                alloc = self._kv_allocated
                mapped = None
            waste = (1.0 - resident / alloc) if alloc else 0.0
            self._kv_last_resident = resident
            self._kv_last_alloc = alloc
            self._kv_resident_sum += resident
            self._kv_alloc_sum += alloc
            self._kv_samples += 1
        if m is None:
            m = self._m()
        if alloc != self._kv_pub_alloc:
            # dense alloc is the static pool — constant across a serve;
            # skip the per-step gauge write unless it actually moved
            self._kv_pub_alloc = alloc
            m["kv_alloc"].set(float(alloc), replica=self.replica)
        m["kv_res"].set(float(resident), replica=self.replica)
        m["kv_waste"].set(waste, replica=self.replica)
        for kv in workloads.ALL_KINDS:
            # an idle kind reads 0, not a frozen last-busy value — but
            # only CHANGED counts pay a gauge write (the first snapshot
            # publishes all five; a steady one-kind serve then writes
            # none), keeping the census inside the bookkeeping budget
            n_kind = kinds.get(kv, 0)
            if self._kind_census_pub.get(kv) != n_kind:
                self._kind_census_pub[kv] = n_kind
                m["active_kind"].set(float(n_kind),
                                     replica=self.replica, kind=kv)
        self._steps += 1
        paged_fields = {} if not self.paged else {
            "kv_mapped_pages": mapped,
            "kv_page_len": self._pages.page_len,
            "kv_pool_bytes": self._kv_allocated,
        }
        if self._prefix is not None:
            # sharing census (ISSUE 16) on every snapshot — the flight
            # recorder doubles as the prefix-cache timeline
            shared = self._pages.shared_pages
            cached = self._prefix.cached_pages
            paged_fields.update(
                kv_used_pages=self._pages.used_pages,
                kv_shared_pages=shared,
                kv_cached_pages=cached,
                kv_cow_copies_total=self._prefix.cow_copies,
                kv_prefix_hits_total=self._prefix.hits,
                kv_prefix_hit_tokens_total=self._prefix.hit_tokens,
            )
            m["kv_shared"].set(float(shared), replica=self.replica)
            m["kv_cached"].set(float(cached), replica=self.replica)
        self.flight_recorder.record_snapshot(
            step=self._steps, slots=slot_ids, queue=queued_ids,
            queue_depth=len(queued_ids),
            request_kinds=kinds,
            occupancy=n_active / self.n_slots,
            kv_allocated_bytes=alloc,
            kv_resident_bytes=resident,
            kv_token_bytes=self._kv_token_bytes,
            kv_waste_ratio=round(waste, 6),
            **paged_fields, **extra)

    def _debug_extra(self):
        """Live state merged into ``flight_recorder.debug_state()`` —
        what ``GET /debug/serving`` shows beyond the recorded past."""
        with self._lock:
            state = {
                "n_slots": self.n_slots,
                "occupancy": sum(r is not None for r in self.slots)
                / self.n_slots,
                "queue_depth": len(self._queue),
                "slots": [None if r is None else r.id
                          for r in self.slots],
                "steps": self._steps,
                "trace_overhead_seconds": round(self._trace_overhead, 6),
            }
        state["kv"] = self.kv_report()
        if hasattr(self.engine, "compile_report"):
            state["compiles"] = self.engine.compile_report()
        if self.slo is not None:
            state["slo"] = self.slo.report()
        return state

    # ---------------------------------------------------- inspection
    @property
    def trace_overhead_seconds(self) -> float:
        """Cumulative host cost of the SLO-plane bookkeeping (trace
        events, snapshots, trace close-out) — the MetricsListener-style
        self-timing the <2% budget test asserts against."""
        return self._trace_overhead

    def occupancy(self) -> float:
        with self._lock:
            return sum(r is not None for r in self.slots) / self.n_slots

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def cache_nbytes(self) -> int:
        return kvcache.cache_nbytes(self.cache)

    def reset_kv_window(self):
        """Restart the KV residency accumulators (running means and
        final-residency samples). Benches call this after warm-up, next
        to swapping in a fresh SLOTracker, so the memory evidence and
        the SLO evidence in one row cover the SAME measured window —
        warm-up's near-empty pool would otherwise bias the waste ratio
        upward. Gauges and flight-recorder snapshots are untouched.

        Takes the metadata ``_lock`` — the lock every accumulator
        writer holds (``_record_snapshot`` updates inside its locked
        block; ``_finish`` runs inside the admit/sweep locked blocks) —
        so a reset never lands between a sum and its count, and never
        waits out a device dispatch."""
        with self._lock:
            self._kv_resident_sum = 0.0
            self._kv_alloc_sum = 0.0
            self._kv_samples = 0
            self._final_res_sum = 0.0
            self._final_res_n = 0
            self._peak_active = 0
        return self

    def kv_report(self) -> dict:
        """KV residency accounting (ISSUE 12), plain data: allocated vs
        resident bytes, running-mean waste ratio over the serve since
        construction (or the last ``reset_kv_window``), per-token
        bytes, and mean final residency. This is the block ``bench.py``
        embeds as a row's ``memory`` evidence and ``GET /debug/memory``
        aggregates across live schedulers.

        Reads under the metadata ``_lock`` — the writers' lock — so a
        live report never sees a sum without its count, and a debug
        endpoint never blocks on an in-flight device sweep (the PR-11
        discipline: device work runs outside the metadata lock)."""
        with self._lock:
            return self._kv_report_locked()

    def _kv_report_locked(self) -> dict:
        # allocated bytes: static pool footprint under dense slotting;
        # MAPPED-page bytes under paging (ISSUE 14) — last snapshot and
        # the window sum, so mean waste is resident-sum over alloc-sum
        # (a ratio of same-window totals, not of mismatched means)
        mean_res = (self._kv_resident_sum / self._kv_samples
                    if self._kv_samples else 0.0)
        if self.paged:
            alloc_last = self._kv_last_alloc
            mean_alloc = (self._kv_alloc_sum / self._kv_samples
                          if self._kv_samples else 0.0)
            waste_mean = (1.0 - self._kv_resident_sum / self._kv_alloc_sum
                          if self._kv_alloc_sum else 0.0)
        else:
            alloc_last = mean_alloc = self._kv_allocated
            waste_mean = (1.0 - mean_res / self._kv_allocated
                          if self._kv_allocated else 0.0)
        out = {
            "allocated_bytes": alloc_last,
            "allocated_bytes_mean": round(mean_alloc, 1),
            "pool_bytes": self._kv_allocated,
            "token_bytes": self._kv_token_bytes,
            "resident_bytes_last": self._kv_last_resident,
            "resident_bytes_mean": round(mean_res, 1),
            "waste_ratio_last": round(1.0 - self._kv_last_resident
                                      / alloc_last, 6) if alloc_last
            else 0.0,
            "waste_ratio_mean": round(waste_mean, 6),
            "snapshots": self._kv_samples,
            "peak_concurrent": self._peak_active,
            "final_residency_mean": round(
                self._final_res_sum / self._final_res_n, 6)
            if self._final_res_n else None,
            "finished_requests": self._final_res_n,
        }
        if self.paged:
            out["paged"] = self._pages.report()
            out["kv_dtype"] = ("int8"
                               if kvcache.is_quantized(self.cache)
                               else str(jnp.dtype(
                                   self.cache["k"].dtype).name))
        if self._prefix is not None:
            # sharing evidence (ISSUE 16): hits, tokens the pool did
            # NOT re-prefill or re-store, CoW splits, evictions
            out["prefix"] = self._prefix.report()
        return out

    def drop_session(self, session_id: str) -> bool:
        """Release a session's retained pages (end of conversation) —
        they become plain cached prefix pages if the block index still
        holds them, else free. Returns True if the session existed."""
        with self._lock:
            if self._prefix is None:
                return False
            return self._prefix.drop_session(session_id)

    def check_pages(self) -> bool:
        """Assert the free-XOR-refcounted page invariant, feeding the
        prefix cache's hold census in as the external refs (the fuzz
        tests' oracle). True for dense pools."""
        with self._lock:
            if not self.paged:
                return True
            return self._pages.check(
                self._prefix.holds() if self._prefix is not None
                else None)
