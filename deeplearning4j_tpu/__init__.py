"""deeplearning4j_tpu — a TPU-native deep learning framework with the
capability surface of Eclipse Deeplearning4j, rebuilt on JAX/XLA.

Quick start (mirrors the reference's MultiLayerNetwork workflow):

    from deeplearning4j_tpu import nd
    from deeplearning4j_tpu.nn import (NeuralNetConfiguration, DenseLayer,
                                       OutputLayer, MultiLayerNetwork)
    from deeplearning4j_tpu.train import Adam
    from deeplearning4j_tpu.data import MnistDataSetIterator

    conf = (NeuralNetConfiguration.builder()
            .seed(123).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_in=784, n_out=256, activation="relu"))
            .layer(OutputLayer(n_in=256, n_out=10, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init((784,))
    net.fit(MnistDataSetIterator(128, train=True, flatten=True), epochs=1)
"""

__version__ = "0.1.0"

from . import ndarray as nd  # noqa: F401 — the Nd4j-style namespace
