"""ROC / ROCBinary / ROCMultiClass — AUC & AUPRC.

Reference parity: ``org.nd4j.evaluation.classification.{ROC, ROCBinary,
ROCMultiClass}``. Like the reference, `threshold_steps=0` means EXACT mode
(store all scores, trapezoidal AUROC) and `threshold_steps=N` uses a fixed
histogram of N thresholds (streaming, O(N) memory — the mode you want for
huge eval sets).
"""

from __future__ import annotations

import numpy as np


def _auc(x, y):
    """Trapezoidal area; x must already be monotone non-decreasing."""
    return float(np.trapezoid(np.asarray(y), np.asarray(x)))


class ROC:
    """Binary ROC: labels (N,) or one-hot (N,2); probs of positive class."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        if threshold_steps:
            self._pos_hist = np.zeros(threshold_steps + 1, np.int64)
            self._neg_hist = np.zeros(threshold_steps + 1, np.int64)
        else:
            self._scores = []
            self._labels = []

    def eval(self, labels, predictions, mask=None):
        y = np.asarray(labels)
        p = np.asarray(predictions)
        if p.ndim == 3:
            p = p.reshape(-1, p.shape[-1])
            y = y.reshape(-1, y.shape[-1]) if y.ndim == 3 else y.reshape(-1)
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                y, p = y[keep], p[keep]
        if p.ndim == 2 and p.shape[-1] == 2:
            p = p[:, 1]
        elif p.ndim == 2:
            p = p[:, 0]
        if y.ndim == 2 and y.shape[-1] == 2:
            y = y[:, 1]
        elif y.ndim == 2:
            y = y[:, 0]
        y = (y > 0.5).astype(np.int64)
        if self.threshold_steps:
            bins = np.clip((p * self.threshold_steps).astype(int), 0, self.threshold_steps)
            np.add.at(self._pos_hist, bins[y == 1], 1)
            np.add.at(self._neg_hist, bins[y == 0], 1)
        else:
            self._scores.append(p)
            self._labels.append(y)

    def _curve(self):
        """Returns (fpr, tpr, precision) with fpr/tpr monotone ascending."""
        if self.threshold_steps:
            # tp[i] = positives with score-bin >= i (threshold descending as
            # i ascends) — reverse so the curve ascends from (0,0) to (1,1)
            pos = self._pos_hist[::-1].cumsum()[::-1].astype(np.float64)
            neg = self._neg_hist[::-1].cumsum()[::-1].astype(np.float64)
            tp = pos[::-1]
            fp = neg[::-1]
            p_total = self._pos_hist.sum() or 1
            n_total = self._neg_hist.sum() or 1
            tpr = np.concatenate([[0.0], tp / p_total])
            fpr = np.concatenate([[0.0], fp / n_total])
            prec = np.concatenate([[1.0], tp / np.maximum(tp + fp, 1)])
            return fpr, tpr, prec
        s = np.concatenate(self._scores)
        y = np.concatenate(self._labels)
        order = np.argsort(-s)
        y = y[order]
        tp = y.cumsum()
        fp = (1 - y).cumsum()
        p_total = y.sum() or 1
        n_total = (1 - y).sum() or 1
        tpr = np.concatenate([[0.0], tp / p_total])
        fpr = np.concatenate([[0.0], fp / n_total])
        prec = np.concatenate([[1.0], tp / np.maximum(tp + fp, 1)])
        return fpr, tpr, prec

    def calculate_auc(self) -> float:
        fpr, tpr, _ = self._curve()
        return _auc(fpr, tpr)

    def calculate_auprc(self) -> float:
        _, tpr, prec = self._curve()
        return _auc(tpr, prec)

    def get_roc_curve(self):
        fpr, tpr, _ = self._curve()
        return fpr, tpr


class ROCBinary:
    """Per-output ROC for multi-label sigmoid outputs."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs = None

    def eval(self, labels, predictions, mask=None):
        y = np.asarray(labels)
        p = np.asarray(predictions)
        c = p.shape[-1]
        if self._rocs is None:
            self._rocs = [ROC(self.threshold_steps) for _ in range(c)]
        for i in range(c):
            self._rocs[i].eval(y[..., i], p[..., i])

    def calculate_auc(self, i: int) -> float:
        return self._rocs[i].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))


class ROCMultiClass:
    """One-vs-all ROC per class (reference ROCMultiClass)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs = None

    def eval(self, labels, predictions, mask=None):
        y = np.asarray(labels)
        p = np.asarray(predictions)
        c = p.shape[-1]
        if y.ndim == 1:
            onehot = np.zeros_like(p)
            onehot[np.arange(len(y)), y.astype(int)] = 1.0
            y = onehot
        if self._rocs is None:
            self._rocs = [ROC(self.threshold_steps) for _ in range(c)]
        for i in range(c):
            self._rocs[i].eval(y[..., i], p[..., i])

    def calculate_auc(self, i: int) -> float:
        return self._rocs[i].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))
