"""deeplearning4j_tpu.eval — evaluation metrics."""

from .classification import ConfusionMatrix, Evaluation, EvaluationBinary
from .regression import RegressionEvaluation
from .roc import ROC, ROCBinary, ROCMultiClass
