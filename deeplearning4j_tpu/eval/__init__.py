"""deeplearning4j_tpu.eval — evaluation metrics."""

from .calibration import EvaluationCalibration
from .classification import ConfusionMatrix, Evaluation, EvaluationBinary
from .regression import RegressionEvaluation
from .roc import ROC, ROCBinary, ROCMultiClass
