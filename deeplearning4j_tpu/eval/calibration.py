"""EvaluationCalibration — probability-calibration analysis.

Reference parity: ``org.nd4j.evaluation.classification.EvaluationCalibration``
(reliability diagram per class, residual plots, probability histograms —
the charts the reference's UI renders for calibration health).

TPU-first: each eval() call is ONE jitted pass vmapped over classes
(per-class scatter-add histograms over the whole (N, C) batch); the host
keeps only the small per-bin accumulators, which merge across batches and
devices like the other evaluators.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(3, 4))
def _calibration_pass(probs, positives, weights, rel_bins, hist_bins):
    """(N, C) probabilities + (N, C) 0/1 positives + (N,) 0/1 weights →
    per-class accumulators: reliability (counts, Σprob, pos) over rel_bins
    and the residual / probability-by-label histograms over hist_bins.
    Masked rows carry weight 0 — shapes stay STATIC so the kernel compiles
    once regardless of how many steps each batch masks out."""

    def per_class(p, y):
        w = weights
        # zero masked rows BEFORE accumulating: padded steps can hold NaN
        # (softmax over fully-masked logits) and NaN * 0 is NaN
        p = jnp.where(w > 0, p, 0.0)
        y = jnp.where(w > 0, y, 0.0)
        ridx = jnp.clip((p * rel_bins).astype(jnp.int32), 0, rel_bins - 1)
        counts = jnp.zeros(rel_bins).at[ridx].add(w)
        prob_sums = jnp.zeros(rel_bins).at[ridx].add(p * w)
        pos = jnp.zeros(rel_bins).at[ridx].add(y * w)
        resid = jnp.abs(y - p)
        hidx = jnp.clip((resid * hist_bins).astype(jnp.int32), 0,
                        hist_bins - 1)
        residual = jnp.zeros(hist_bins).at[hidx].add(w)
        pidx = jnp.clip((p * hist_bins).astype(jnp.int32), 0, hist_bins - 1)
        hist_all = jnp.zeros(hist_bins).at[pidx].add(w)
        hist_pos = jnp.zeros(hist_bins).at[pidx].add(y * w)
        return counts, prob_sums, pos, residual, hist_all, hist_pos

    return jax.vmap(per_class, in_axes=1)(probs, positives)


class EvaluationCalibration:
    """Reliability/residual/probability-histogram accumulator."""

    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.reliability_bins = int(reliability_bins)
        self.histogram_bins = int(histogram_bins)
        self._n_classes = None

    def _ensure(self, n_classes):
        if self._n_classes is None:
            self._n_classes = n_classes
            rb, hb = self.reliability_bins, self.histogram_bins
            self._counts = np.zeros((n_classes, rb))
            self._prob_sums = np.zeros((n_classes, rb))
            self._pos = np.zeros((n_classes, rb))
            self._residual_hist = np.zeros((n_classes, hb))
            self._prob_hist_pos = np.zeros((n_classes, hb))
            self._prob_hist_neg = np.zeros((n_classes, hb))
        elif n_classes != self._n_classes:
            raise ValueError(f"class count changed: {self._n_classes} → "
                             f"{n_classes}")

    def _require_data(self):
        if self._n_classes is None:
            raise ValueError(
                "EvaluationCalibration has no data — eval() was never "
                "called (empty iterator?)")

    # ------------------------------------------------------------ accumulate
    def eval(self, labels, predictions, mask=None):
        """labels (N, C) one-hot (or (N,) indices), predictions (N, C)
        probabilities. RNN shapes (B, T, C) are flattened with `mask`
        (B, T) selecting valid steps — same convention as Evaluation."""
        p = jnp.asarray(predictions)
        y = jnp.asarray(labels)
        w = None
        if p.ndim == 3:
            b, t, c = p.shape
            p = p.reshape(b * t, c)
            y = y.reshape(b * t, -1) if y.ndim == 3 else y.reshape(b * t)
            if mask is not None:
                # weight, don't compress: boolean indexing would make the
                # row count data-dependent and recompile per batch
                w = (jnp.asarray(mask).reshape(b * t) > 0).astype(jnp.float32)
        if y.ndim == 1:
            y = jax.nn.one_hot(y.astype(jnp.int32), p.shape[-1])
        if w is None:
            w = jnp.ones(p.shape[0], jnp.float32)
        self._ensure(p.shape[-1])
        counts, sums, pos, residual, hist_all, hist_pos = _calibration_pass(
            p, (y > 0.5).astype(jnp.float32), w,
            self.reliability_bins, self.histogram_bins)
        self._counts += np.asarray(counts)
        self._prob_sums += np.asarray(sums)
        self._pos += np.asarray(pos)
        self._residual_hist += np.asarray(residual)
        hist_pos = np.asarray(hist_pos)
        self._prob_hist_pos += hist_pos
        self._prob_hist_neg += np.asarray(hist_all) - hist_pos
        return self

    def merge(self, other: "EvaluationCalibration") -> "EvaluationCalibration":
        if (other.reliability_bins != self.reliability_bins
                or other.histogram_bins != self.histogram_bins):
            raise ValueError(
                f"cannot merge: bin configs differ "
                f"({self.reliability_bins}/{self.histogram_bins} vs "
                f"{other.reliability_bins}/{other.histogram_bins})")
        if other._n_classes is None:
            return self
        self._ensure(other._n_classes)
        for attr in ("_counts", "_prob_sums", "_pos", "_residual_hist",
                     "_prob_hist_pos", "_prob_hist_neg"):
            setattr(self, attr, getattr(self, attr) + getattr(other, attr))
        return self

    # --------------------------------------------------------------- queries
    def reliability_info(self, class_idx: int):
        """(bin_centers, mean_predicted, fraction_positives, counts) — the
        reliability diagram for one class (reference getReliabilityInfo)."""
        self._require_data()
        rb = self.reliability_bins
        counts = self._counts[class_idx]
        safe = np.maximum(counts, 1)
        return ((np.arange(rb) + 0.5) / rb,
                self._prob_sums[class_idx] / safe,
                self._pos[class_idx] / safe,
                counts.astype(np.int64))

    def expected_calibration_error(self, class_idx: int = None) -> float:
        """ECE: count-weighted |mean predicted − fraction positive|."""
        self._require_data()
        classes = (range(self._n_classes) if class_idx is None
                   else [class_idx])
        num, denom = 0.0, 0.0
        for c in classes:
            _, mean_p, frac_pos, counts = self.reliability_info(c)
            num += float(np.sum(counts * np.abs(mean_p - frac_pos)))
            denom += float(np.sum(counts))
        return num / max(denom, 1.0)

    def residual_plot(self, class_idx: int):
        """(bin_centers, counts) histogram of |label − prob|."""
        self._require_data()
        hb = self.histogram_bins
        return ((np.arange(hb) + 0.5) / hb,
                self._residual_hist[class_idx].astype(np.int64))

    def probability_histogram(self, class_idx: int, positive: bool = True):
        """(bin_centers, counts) of predicted probability, split by the
        true label (reference's positive/negative histograms)."""
        self._require_data()
        hb = self.histogram_bins
        hist = (self._prob_hist_pos if positive
                else self._prob_hist_neg)[class_idx]
        return (np.arange(hb) + 0.5) / hb, hist.astype(np.int64)

    def stats(self) -> str:
        if self._n_classes is None:
            return "EvaluationCalibration: no data"
        lines = [f"EvaluationCalibration ({self.reliability_bins} bins, "
                 f"{int(self._counts[0].sum())} samples/class)"]
        for c in range(self._n_classes):
            lines.append(f"  class {c}: ECE="
                         f"{self.expected_calibration_error(c):.4f}")
        lines.append(f"  overall ECE={self.expected_calibration_error():.4f}")
        return "\n".join(lines)
