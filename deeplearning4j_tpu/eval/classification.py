"""Evaluation — classification metrics.

Reference parity: ``org.nd4j.evaluation.classification.Evaluation``
(accuracy, precision/recall/F1 micro/macro, MCC, GMeasure, confusion matrix,
topN accuracy, per-class stats, stats() report) and ``EvaluationBinary``
(per-output multi-label).

TPU-first: the per-batch accumulation is a jitted confusion-matrix update
(one scatter-add on device); host code only formats the report. Accumulators
merge across batches and across devices (psum-compatible counts).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _confusion_update(conf, labels_idx, preds_idx):
    n = conf.shape[0]
    flat = labels_idx * n + preds_idx
    upd = jnp.zeros((n * n,), jnp.int64 if conf.dtype == jnp.int64 else jnp.int32)
    upd = upd.at[flat].add(1)
    return conf + upd.reshape(n, n)


def _topn_hits(labels_idx, probs, n):
    _, top = jax.lax.top_k(probs, n)
    return jnp.sum(jnp.any(top == labels_idx[:, None], axis=1))


class ConfusionMatrix:
    def __init__(self, num_classes: int):
        self.matrix = np.zeros((num_classes, num_classes), np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def to_numpy(self):
        return self.matrix


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1,
                 labels_list=None):
        self.num_classes = num_classes
        self.top_n = top_n
        self.labels_list = labels_list
        self._conf = None if num_classes is None else jnp.zeros(
            (num_classes, num_classes), jnp.int32)
        self._topn_correct = 0
        self._count = 0

    # ------------------------------------------------------------------ eval
    def eval(self, labels, predictions, mask=None):
        """labels: one-hot or int ids; predictions: probabilities/logits.

        RNN shapes (B,T,C) are flattened with `mask` (B,T) selecting steps.
        """
        labels = jnp.asarray(labels)
        preds = jnp.asarray(predictions)
        if preds.ndim == 3:  # time series → flatten valid steps
            b, t, c = preds.shape
            preds = preds.reshape(b * t, c)
            labels = labels.reshape(b * t, -1) if labels.ndim == 3 else labels.reshape(b * t)
            if mask is not None:
                keep = np.asarray(mask).reshape(b * t) > 0
                preds = preds[np.asarray(keep)]
                labels = labels[np.asarray(keep)]
        c = preds.shape[-1]
        if self.num_classes is None:
            self.num_classes = int(c)
            self._conf = jnp.zeros((c, c), jnp.int32)
        li = jnp.argmax(labels, -1) if labels.ndim > 1 else labels.astype(jnp.int32)
        pi = jnp.argmax(preds, -1)
        self._conf = _confusion_update(self._conf, li, pi)
        if self.top_n > 1:
            self._topn_correct += int(_topn_hits(li, preds, min(self.top_n, c)))
        self._count += int(li.shape[0])

    def merge(self, other: "Evaluation"):
        if self._conf is None:
            self._conf = other._conf
        elif other._conf is not None:
            self._conf = self._conf + other._conf
        self._topn_correct += other._topn_correct
        self._count += other._count
        return self

    # ---------------------------------------------------------------- stats
    @property
    def confusion(self) -> np.ndarray:
        return np.zeros((0, 0), np.int64) if self._conf is None else np.asarray(self._conf)

    def accuracy(self) -> float:
        m = self.confusion
        tot = m.sum()
        return float(np.trace(m) / tot) if tot else 0.0

    def top_n_accuracy(self) -> float:
        return self._topn_correct / self._count if self._count else 0.0

    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def _fp(self):
        return self.confusion.sum(0) - self._tp()

    def _fn(self):
        return self.confusion.sum(1) - self._tp()

    def precision(self, cls: Optional[int] = None, average: str = "macro") -> float:
        tp, fp = self._tp(), self._fp()
        if cls is not None:
            d = tp[cls] + fp[cls]
            return float(tp[cls] / d) if d else 0.0
        if average == "micro":
            d = tp.sum() + fp.sum()
            return float(tp.sum() / d) if d else 0.0
        per = np.divide(tp, tp + fp, out=np.zeros_like(tp), where=(tp + fp) > 0)
        seen = (self.confusion.sum(1) + self.confusion.sum(0)) > 0
        return float(per[seen].mean()) if seen.any() else 0.0

    def recall(self, cls: Optional[int] = None, average: str = "macro") -> float:
        tp, fn = self._tp(), self._fn()
        if cls is not None:
            d = tp[cls] + fn[cls]
            return float(tp[cls] / d) if d else 0.0
        if average == "micro":
            d = tp.sum() + fn.sum()
            return float(tp.sum() / d) if d else 0.0
        per = np.divide(tp, tp + fn, out=np.zeros_like(tp), where=(tp + fn) > 0)
        seen = (self.confusion.sum(1) + self.confusion.sum(0)) > 0
        return float(per[seen].mean()) if seen.any() else 0.0

    def f1(self, cls: Optional[int] = None, average: str = "macro") -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        if average == "micro":
            p, r = self.precision(average="micro"), self.recall(average="micro")
            return 2 * p * r / (p + r) if (p + r) else 0.0
        tp, fp, fn = self._tp(), self._fp(), self._fn()
        per_p = np.divide(tp, tp + fp, out=np.zeros_like(tp), where=(tp + fp) > 0)
        per_r = np.divide(tp, tp + fn, out=np.zeros_like(tp), where=(tp + fn) > 0)
        s = per_p + per_r
        per_f = np.divide(2 * per_p * per_r, s, out=np.zeros_like(tp), where=s > 0)
        seen = (self.confusion.sum(1) + self.confusion.sum(0)) > 0
        return float(per_f[seen].mean()) if seen.any() else 0.0

    def gmeasure(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls) if cls is not None else self.precision(average="macro")
        r = self.recall(cls) if cls is not None else self.recall(average="macro")
        return math.sqrt(p * r)

    def matthews_correlation(self) -> float:
        """Multiclass MCC (R_k statistic), like the reference."""
        c = self.confusion.astype(np.float64)
        t = c.sum()
        if t == 0:
            return 0.0
        s = np.trace(c)
        pk = c.sum(0)
        tk = c.sum(1)
        num = s * t - tk @ pk
        den = math.sqrt(max(t * t - (pk @ pk), 0)) * math.sqrt(max(t * t - (tk @ tk), 0))
        return float(num / den) if den else 0.0

    def false_positive_rate(self, cls: int) -> float:
        m = self.confusion
        fp = self._fp()[cls]
        tn = m.sum() - m.sum(0)[cls] - m.sum(1)[cls] + m[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def false_negative_rate(self, cls: int) -> float:
        fn, tp = self._fn()[cls], self._tp()[cls]
        return float(fn / (fn + tp)) if (fn + tp) else 0.0

    def stats(self) -> str:
        m = self.confusion
        n = m.shape[0]
        names = self.labels_list or [str(i) for i in range(n)]
        lines = ["", "========================Evaluation Metrics========================",
                 f" # of classes:    {n}",
                 f" Accuracy:        {self.accuracy():.4f}"]
        if self.top_n > 1:
            lines.append(f" Top {self.top_n} Accuracy:  {self.top_n_accuracy():.4f}")
        lines += [f" Precision:       {self.precision(average='macro'):.4f}",
                  f" Recall:          {self.recall(average='macro'):.4f}",
                  f" F1 Score:        {self.f1(average='macro'):.4f}",
                  f" MCC:             {self.matthews_correlation():.4f}",
                  "", "=========================Confusion Matrix========================="]
        header = "     " + " ".join(f"{nm:>6}" for nm in names)
        lines.append(header)
        for i in range(n):
            lines.append(f"{names[i]:>4} " + " ".join(f"{int(m[i, j]):>6}" for j in range(n)))
        lines.append("==================================================================")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()


class EvaluationBinary:
    """Per-output binary metrics for multi-label sigmoid outputs
    (reference EvaluationBinary)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = None
        self.fp = None
        self.fn = None
        self.tn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions) > self.threshold
        lab = labels > 0.5
        w = np.ones_like(lab, np.float64) if mask is None else np.broadcast_to(
            np.asarray(mask, np.float64).reshape(labels.shape[0], -1), labels.shape)
        tp = ((preds & lab) * w).sum(0)
        fp = ((preds & ~lab) * w).sum(0)
        fn = ((~preds & lab) * w).sum(0)
        tn = ((~preds & ~lab) * w).sum(0)
        if self.tp is None:
            self.tp, self.fp, self.fn, self.tn = tp, fp, fn, tn
        else:
            self.tp += tp
            self.fp += fp
            self.fn += fn
            self.tn += tn

    def accuracy(self, i: int) -> float:
        tot = self.tp[i] + self.fp[i] + self.fn[i] + self.tn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        n = len(self.tp)
        lines = ["Label  Acc     Prec    Rec     F1"]
        for i in range(n):
            lines.append(f"{i:<6}{self.accuracy(i):<8.4f}{self.precision(i):<8.4f}"
                         f"{self.recall(i):<8.4f}{self.f1(i):<8.4f}")
        return "\n".join(lines)
