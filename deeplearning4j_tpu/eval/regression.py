"""RegressionEvaluation — per-column regression metrics.

Reference parity: ``org.nd4j.evaluation.regression.RegressionEvaluation``
(MSE, MAE, RMSE, RSE, pearson correlation, R^2, per-column + stats()).
Accumulates streaming sums so batches merge exactly.
"""

from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns=None, column_names=None):
        self.n = 0
        self.n_columns = n_columns
        self.column_names = column_names
        self._sums = None

    def _ensure(self, c):
        if self._sums is None:
            self.n_columns = c
            z = np.zeros(c, np.float64)
            self._sums = {k: z.copy() for k in
                          ("err2", "abs_err", "label", "label2", "pred", "pred2", "lp")}

    def eval(self, labels, predictions, mask=None):
        y = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        if y.ndim == 3:
            y = y.reshape(-1, y.shape[-1])
            p = p.reshape(-1, p.shape[-1])
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                y, p = y[keep], p[keep]
        self._ensure(y.shape[-1])
        d = p - y
        s = self._sums
        s["err2"] += (d * d).sum(0)
        s["abs_err"] += np.abs(d).sum(0)
        s["label"] += y.sum(0)
        s["label2"] += (y * y).sum(0)
        s["pred"] += p.sum(0)
        s["pred2"] += (p * p).sum(0)
        s["lp"] += (y * p).sum(0)
        self.n += y.shape[0]

    def merge(self, other):
        if self._sums is None:
            self._sums, self.n, self.n_columns = other._sums, other.n, other.n_columns
        elif other._sums is not None:
            for k in self._sums:
                self._sums[k] += other._sums[k]
            self.n += other.n
        return self

    def mean_squared_error(self, col: int) -> float:
        return float(self._sums["err2"][col] / self.n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sums["abs_err"][col] / self.n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        s = self._sums
        mean_label = s["label"][col] / self.n
        denom = s["label2"][col] - 2 * mean_label * s["label"][col] + self.n * mean_label ** 2
        return float(s["err2"][col] / denom) if denom else 0.0

    def pearson_correlation(self, col: int) -> float:
        s = self._sums
        n = self.n
        cov = s["lp"][col] - s["label"][col] * s["pred"][col] / n
        vl = s["label2"][col] - s["label"][col] ** 2 / n
        vp = s["pred2"][col] - s["pred"][col] ** 2 / n
        d = np.sqrt(max(vl * vp, 0.0))
        return float(cov / d) if d else 0.0

    def r_squared(self, col: int) -> float:
        return 1.0 - self.relative_squared_error(col)

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(i) for i in range(self.n_columns)]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(i) for i in range(self.n_columns)]))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean([self.root_mean_squared_error(i) for i in range(self.n_columns)]))

    def average_r_squared(self) -> float:
        return float(np.mean([self.r_squared(i) for i in range(self.n_columns)]))

    def stats(self) -> str:
        names = self.column_names or [f"col_{i}" for i in range(self.n_columns)]
        lines = [f"{'Column':<12}{'MSE':>12}{'MAE':>12}{'RMSE':>12}{'RSE':>12}{'PC':>12}{'R^2':>12}"]
        for i in range(self.n_columns):
            lines.append(f"{names[i]:<12}{self.mean_squared_error(i):>12.5f}"
                         f"{self.mean_absolute_error(i):>12.5f}"
                         f"{self.root_mean_squared_error(i):>12.5f}"
                         f"{self.relative_squared_error(i):>12.5f}"
                         f"{self.pearson_correlation(i):>12.5f}"
                         f"{self.r_squared(i):>12.5f}")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()
