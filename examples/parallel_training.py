"""Data-parallel training with ParallelWrapper on a device mesh.

Mirrors the reference's ParallelWrapper example (multi-GPU averaging) the
TPU-native way: a jax.sharding.Mesh + ParallelWrapper shards each global
batch across devices and lets XLA insert the gradient psum over ICI. On a
CPU host this runs on 8 virtual devices; on a TPU pod slice the same code
uses the real chips. Run: python examples/parallel_training.py [--smoke]
"""

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import ParallelInference, ParallelWrapper, make_mesh
from deeplearning4j_tpu.train import Adam

conf = (NeuralNetConfiguration.builder()
        .seed(1).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_in=784, n_out=128, activation="relu"))
        .layer(OutputLayer(n_in=128, n_out=10, activation="softmax"))
        .build())
net = MultiLayerNetwork(conf)
net.init((784,))

mesh = make_mesh(dp=8)
pw = ParallelWrapper(net, mesh=mesh)
n = 1024 if args.smoke else 8192
pw.fit(MnistDataSetIterator(batch_size=256, flatten=True, train=True, num_examples=n,
                            seed=1))

pi = ParallelInference(net, mesh=mesh)
import numpy as np
x = np.random.default_rng(0).random((64, 784)).astype(np.float32)
out = np.asarray(pi.output(x))
assert out.shape == (64, 10)
print("OK dp-sharded fit + sharded inference on", mesh.devices.size,
      "devices")
