"""Character-level text generation with GravesLSTM.

Mirrors the reference's LSTMCharModellingExample: train a 2-layer LSTM
char model on a text corpus, then sample new text one streamed
rnn_time_step at a time. Run: python examples/char_rnn_generation.py
[--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.zoo import TextGenerationLSTM

CORPUS = (
    "deep learning on tpus is a matter of feeding the matrix units. "
    "keep the tensors large and the dtypes small. "
    "the systolic array eats batches of matmuls for breakfast. "
) * (4 if args.smoke else 64)

chars = sorted(set(CORPUS))
vocab = len(chars)
idx = {c: i for i, c in enumerate(chars)}
seq = 32
units = 64 if args.smoke else 256

model = TextGenerationLSTM(num_classes=vocab, input_shape=(seq, vocab),
                           units=units)
net = model.init()

# build (B, T, vocab) one-hot windows
data = np.asarray([idx[c] for c in CORPUS], np.int32)
starts = np.arange(0, len(data) - seq - 1, seq)
x = np.eye(vocab, dtype=np.float32)[
    np.stack([data[s:s + seq] for s in starts])]
y = np.eye(vocab, dtype=np.float32)[
    np.stack([data[s + 1:s + seq + 1] for s in starts])]

from deeplearning4j_tpu.data.dataset import DataSet

epochs = 3 if args.smoke else 20
for e in range(epochs):
    loss = net.fit(DataSet(x, y))
print(f"final loss {loss:.3f}")

prime = "deep learning "
pr = np.eye(vocab, dtype=np.float32)[
    np.asarray([idx[c] for c in prime], np.int32)][None]
ids = model.generate(net, pr, n_steps=80, temperature=0.7)
text = "".join(chars[i] for i in np.asarray(ids)[0])
print("generated:", prime + text)
assert len(text) == 80
print("OK")
