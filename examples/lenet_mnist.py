"""LeNet on MNIST — conv/pool/dense with batch normalization.

Mirrors the reference's LenetMnistExample (conv→pool→conv→pool→dense→out)
with a BatchNormalization layer on the stem and a save/load round trip via
ModelSerializer. Run: python examples/lenet_mnist.py [--smoke]
"""

import tempfile

import numpy as np

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (BatchNormalization, ConvolutionLayer,
                                   DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   SubsamplingLayer)
from deeplearning4j_tpu.serde import ModelSerializer
from deeplearning4j_tpu.train import Adam

n = 2048 if args.smoke else 8192
epochs = 3 if args.smoke else 3

conf = (NeuralNetConfiguration.builder()
        .seed(123)
        .updater(Adam(1e-3))
        .list()
        .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5),
                                activation="identity"))
        .layer(BatchNormalization(activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(n_out=16, kernel_size=(5, 5),
                                activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=64, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build())

net = MultiLayerNetwork(conf)
net.init()
net.fit(MnistDataSetIterator(batch_size=128, train=True, num_examples=n,
                             seed=123), epochs=epochs)
ev = net.evaluate(MnistDataSetIterator(batch_size=128, train=False,
                                       num_examples=max(n // 4, 512),
                                       seed=123))
print(ev.stats())

with tempfile.NamedTemporaryFile(suffix=".zip") as f:
    ModelSerializer.write_model(net, f.name)
    net2 = ModelSerializer.restore_multi_layer_network(f.name)
x = np.random.default_rng(0).random((4, 28, 28, 1)).astype(np.float32)
assert (np.asarray(net.output(x)) == np.asarray(net2.output(x))).all()
assert ev.accuracy() > (0.80 if args.smoke else 0.95), ev.accuracy()
print(f"OK accuracy={ev.accuracy():.4f}, save/load bit-identical")
