"""Transfer learning: freeze a trained feature extractor, retrain the head.

Mirrors the reference's TransferLearning examples
(TransferLearning.Builder: setFeatureExtractor + nOutReplace). Trains a
small conv net on digits 0-4, then adapts it to all 10 classes with the
conv stack frozen. Run: python examples/transfer_learning.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (ConvolutionLayer, DenseLayer,
                                   FineTuneConfiguration, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   SubsamplingLayer, TransferLearning)
from deeplearning4j_tpu.train import Adam

n = 2048 if args.smoke else 4096

conf = (NeuralNetConfiguration.builder()
        .seed(7).updater(Adam(1e-3)).list()
        .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5),
                                activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build())
base = MultiLayerNetwork(conf)
base.init()
base.fit(MnistDataSetIterator(batch_size=128, train=True, num_examples=n,
                              seed=7), epochs=1)

# freeze everything up to the dense layer; replace the 10-way head
tuned = (TransferLearning.Builder(base)
         .fine_tune_configuration(FineTuneConfiguration(updater=Adam(5e-4)))
         .set_feature_extractor(2)        # freeze layers 0..2
         .nout_replace(3, 10)             # fresh head
         .build())
tuned.fit(MnistDataSetIterator(batch_size=128, train=True, num_examples=n,
                               seed=8), epochs=1)
ev = tuned.evaluate(MnistDataSetIterator(batch_size=128, train=False,
                                         num_examples=512, seed=9))
print(ev.stats())

# frozen conv weights must be bit-identical to the base network's
w_base = np.asarray(base.params["layer_0"]["W"])
w_tuned = np.asarray(tuned.params["layer_0"]["W"])
assert (w_base == w_tuned).all(), "frozen layer moved!"
print(f"OK accuracy={ev.accuracy():.4f}, frozen layers untouched")
