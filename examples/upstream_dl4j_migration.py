"""Migrating from upstream Deeplearning4j — checkpoint interop both ways.

A DL4J user holds ``ModelSerializer.writeModel`` zips
(configuration.json + coefficients.bin + updaterState.bin +
normalizer.bin). This example round-trips that exact layout: train a
net here, export it in the upstream format, restore it as if it came
from a JVM deployment (auto-detected by the facade), and keep training
— the Adam state and fitted normalizer ride along.
Run: python examples/upstream_dl4j_migration.py [--smoke]
"""

import tempfile

import numpy as np

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
from deeplearning4j_tpu.nn import (DenseLayer, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serde import ModelSerializer
from deeplearning4j_tpu.train import Adam

rng = np.random.default_rng(7)
n = 256 if args.smoke else 2048
x = rng.normal(size=(n, 10)).astype(np.float32) * 2.0 + 0.5
y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]

norm = NormalizerStandardize()
ds = DataSet(x, y)
norm.fit([ds])
ds = norm.transform(ds)

conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(5e-3))
        .list()
        .layer(DenseLayer(n_in=10, n_out=32, activation="relu"))
        .layer(OutputLayer(n_in=32, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
for _ in range(4):
    net.fit(ds)
print(f"trained: score={net.score(ds):.4f}")

with tempfile.TemporaryDirectory() as td:
    zip_path = td + "/dl4j_model.zip"
    # the upstream ModelSerializer layout — loadable by any DL4J tooling
    ModelSerializer.write_model_upstream_format(
        net, zip_path, save_updater=True, normalizer=norm)

    # ...and back: the facade auto-detects upstream zips
    restored = ModelSerializer.restore_multi_layer_network(zip_path)
    out_a = np.asarray(net.output(ds.features[:8]))
    out_b = np.asarray(restored.output(ds.features[:8]))
    assert np.allclose(out_a, out_b, rtol=1e-6, atol=1e-7)
    print("restored forward matches exported net bit-for-bit-ish:",
          float(np.abs(out_a - out_b).max()))

    assert restored.normalizer is not None
    print("normalizer restored: mean[0] =",
          float(restored.normalizer.mean[0]))

    # continued training resumes the Adam m/v/count — trajectories match
    for _ in range(2):
        net.fit(ds)
        restored.fit(ds)
    drift = float(np.abs(np.asarray(net.params_flat())
                         - np.asarray(restored.params_flat())).max())
    assert drift < 1e-5, drift
    print(f"resumed training matches the uninterrupted run (drift {drift:.2e})")

print("OK")
