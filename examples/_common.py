"""Shared example boilerplate.

Forces the CPU backend by default so examples run anywhere (set
EXAMPLES_ON_TPU=1 to use the real chip), and provides the --smoke flag
every example supports (tiny sizes, a few seconds on CPU — the mode CI
runs)."""

import argparse
import os
import pathlib
import sys

# the repo root (works without pip-installing the package)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def setup(description: str):
    if not os.environ.get("EXAMPLES_ON_TPU"):
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes for a fast functional check")
    return ap.parse_args()
