"""Activation rematerialization — train a deep residual net in a fraction
of the activation memory, with identical numerics.

``net.remat_segments = n`` runs the training forward as n
``jax.checkpoint`` segments, cut where the fewest activations cross a
boundary (a liveness pass over the DAG). Only segment-boundary
activations are stored for the backward pass; everything inside a segment
is recomputed. On an HBM-bandwidth-bound step the recompute rides
otherwise-idle MXU cycles. The reference has no analogue (cuDNN-era
workspaces trade memory differently); in this framework it is one
attribute on any MultiLayerNetwork / ComputationGraph, and
``ResNet50(remat_segments=n)`` in the zoo.

This example trains the same residual CNN twice — monolithic and with 4
checkpoint segments — and verifies the parameter trajectories agree.
Run: python examples/activation_remat.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import (ActivationLayer, BatchNormalization,
                                   ComputationGraph, ConvolutionLayer,
                                   ElementWiseVertex, InputType,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train import Sgd


def build(n_blocks):
    b = NeuralNetConfiguration.builder().seed(42).updater(Sgd(0.05))
    g = b.graph_builder().add_inputs("in")
    g.add_layer("stem", ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                         convolution_mode="same",
                                         activation="identity"), "in")
    g.add_layer("stem_bn", BatchNormalization(activation="relu"), "stem")
    x = "stem_bn"
    for i in range(n_blocks):
        g.add_layer(f"b{i}_conv", ConvolutionLayer(
            n_out=16, kernel_size=(3, 3), convolution_mode="same",
            activation="identity"), x)
        g.add_layer(f"b{i}_bn", BatchNormalization(activation="identity"),
                    f"b{i}_conv")
        g.add_vertex(f"b{i}_add", ElementWiseVertex(op="add"),
                     f"b{i}_bn", x)
        g.add_layer(f"b{i}_relu", ActivationLayer(activation="relu"),
                    f"b{i}_add")
        x = f"b{i}_relu"
    g.add_layer("out", OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"), x)
    g.set_outputs("out")
    g.set_input_types(InputType.convolutional(16, 16, 3))
    return ComputationGraph(g.build()).init()


n_blocks = 3 if args.smoke else 8
steps = 4 if args.smoke else 30
rng = np.random.default_rng(0)
x = jnp.asarray(rng.standard_normal((32, 16, 16, 3)), jnp.float32)
y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)])
ds = DataSet(x, y)

plain = build(n_blocks)
remat = build(n_blocks)
remat.remat_segments = 4     # 4 checkpoint segments; boundaries auto-chosen

for _ in range(steps):
    l0 = plain.fit([ds])
    l1 = remat.fit([ds])

drift = max(float(jnp.max(jnp.abs(a - b)))
            for a, b in zip(jax.tree_util.tree_leaves(plain.params),
                            jax.tree_util.tree_leaves(remat.params)))
print(f"final loss  plain={l0:.4f}  remat={l1:.4f}")
print(f"max param drift after {steps} steps: {drift:.2e}")
assert drift < 1e-4, "remat must be an execution-strategy change only"

plan = remat._segment_plan(4, ["in"])
print("checkpoint boundaries (1 tensor crosses each):",
      [seg["carry_in"] for seg in plan])
print("OK")
