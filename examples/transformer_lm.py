"""Train a small GPT-style transformer LM from the zoo.

The zoo transformer ships the TPU-tuned defaults adjudicated on-chip
(docs/PERF.md): bf16 compute, rematerialization (the "save_attn" policy
pins attention outputs so backward skips re-running the T^2 op in the
block's downstream recompute), fused chunked LM cross-entropy (the
(B,T,V) logits are never materialized), bf16 score materialization on
the XLA attention path, and — on a single real TPU at T>=1024 — the
pallas flash attention kernel with grad-tuned block sizes.
Run: python examples/transformer_lm.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.zoo import transformer as tfm

cfg = tfm.TransformerConfig(
    vocab_size=256, d_model=64 if args.smoke else 256,
    n_heads=4, n_layers=2 if args.smoke else 4,
    d_ff=128 if args.smoke else 1024, max_seq=64,
    dtype=jnp.float32 if args.smoke else jnp.bfloat16)

key = jax.random.PRNGKey(0)
params = tfm.init_params(key, cfg)
opt = optax.adamw(3e-4)
opt_state = opt.init(params)
step = jax.jit(tfm.make_train_step(cfg, opt), donate_argnums=(0, 1))

# byte-level language modeling on a repeated pattern
rng = np.random.default_rng(0)
text = np.frombuffer((b"the quick brown tpu jumps over the lazy gpu. " * 64),
                     dtype=np.uint8).astype(np.int32)
steps = 10 if args.smoke else 200
batch = 8
losses = []
for i in range(steps):
    starts = rng.integers(0, len(text) - cfg.max_seq - 1, batch)
    ids = jnp.asarray(np.stack([text[s:s + cfg.max_seq] for s in starts]))
    tgt = jnp.asarray(np.stack([text[s + 1:s + cfg.max_seq + 1]
                                for s in starts]))
    params, opt_state, loss = step(params, opt_state, ids, tgt)
    losses.append(float(loss))
print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")
assert losses[-1] < losses[0], "loss did not decrease"
print("OK")
