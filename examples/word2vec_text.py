"""Word2Vec on a toy corpus: train embeddings, query nearest words.

Mirrors the reference's Word2VecRawTextExample (skip-gram with negative
sampling; CBOW and hierarchical softmax are flags away). Run:
python examples/word2vec_text.py [--smoke]
"""

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.nlp import Word2Vec

SENTENCES = [
    "the tpu runs matrix multiplications on the systolic array",
    "the gpu runs kernels on streaming multiprocessors",
    "a tpu chip has fast hbm memory and a matrix unit",
    "a gpu card has fast hbm memory and tensor cores",
    "training needs data parallel sharding across chips",
    "inference needs low latency on a single chip",
    "the compiler fuses elementwise work into the matmul",
    "the scheduler overlaps transfers with compute",
] * (8 if args.smoke else 64)

w2v = Word2Vec(min_word_frequency=2,
               layer_size=32 if args.smoke else 128,
               window_size=3, seed=11,
               epochs=2 if args.smoke else 10)
w2v.fit(SENTENCES)

for q in ("tpu", "memory"):
    print(q, "->", w2v.words_nearest(q, 4))
sim = w2v.similarity("tpu", "gpu")
print(f"similarity(tpu, gpu) = {sim:.3f}")
assert -1.0 <= sim <= 1.0
print("OK")
