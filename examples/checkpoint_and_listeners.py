"""Training listeners + periodic checkpoints with retention.

Mirrors the reference's listener examples (ScoreIterationListener,
CheckpointListener with keepLast): scores print as training runs,
checkpoints rotate on disk, and training resumes from the newest one.
Run: python examples/checkpoint_and_listeners.py [--smoke]
"""

import pathlib
import tempfile

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (CheckpointListener, DenseLayer,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   ScoreIterationListener)
from deeplearning4j_tpu.serde import ModelSerializer
from deeplearning4j_tpu.train import Adam

conf = (NeuralNetConfiguration.builder()
        .seed(5).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_in=784, n_out=64, activation="relu"))
        .layer(OutputLayer(n_in=64, n_out=10, activation="softmax"))
        .build())
net = MultiLayerNetwork(conf)
net.init((784,))

n = 2048 if args.smoke else 8192
with tempfile.TemporaryDirectory() as td:
    net.set_listeners(
        ScoreIterationListener(print_iterations=8),
        CheckpointListener(td, save_every_n_iterations=8, keep_last=2))
    net.fit(MnistDataSetIterator(batch_size=128, flatten=True, train=True,
                                 num_examples=n, seed=5), epochs=2)
    ckpts = sorted(pathlib.Path(td).glob("checkpoint_*.zip"))
    print("checkpoints on disk:", [c.name for c in ckpts])
    assert len(ckpts) == 2, "retention should keep exactly 2"

    resumed = ModelSerializer.restore_multi_layer_network(str(ckpts[-1]))
    resumed.fit(MnistDataSetIterator(batch_size=128, flatten=True,
                                     train=True, num_examples=512, seed=6))
print("OK — resumed training from the newest checkpoint")
