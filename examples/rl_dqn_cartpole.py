"""Deep Q-learning on CartPole.

Mirrors the reference's RL4J QLearningDiscrete example: replay buffer,
target network, epsilon-greedy policy — the TD step is one jitted
program. Run: python examples/rl_dqn_cartpole.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.rl import CartPoleEnv, DQN, QLearningConfiguration

episodes = 12 if args.smoke else 80
env = CartPoleEnv(seed=1, max_steps=200)
cfg = QLearningConfiguration(
    seed=1, warmup_steps=100 if args.smoke else 200,
    eps_decay_steps=800 if args.smoke else 2000,
    batch_size=64, target_update_freq=200, learning_rate=1e-3,
    max_episode_steps=200)
agent = DQN(env, cfg)
rewards = agent.train(episodes=episodes)
print(f"episode rewards: first={rewards[0]:.0f} "
      f"mean(last 5)={np.mean(rewards[-5:]):.0f}")
score = agent.play(max_steps=200)
print(f"greedy play: {score:.0f} steps balanced")
if not args.smoke:
    assert np.mean(rewards[-10:]) > np.mean(rewards[:10])
print("OK")
