"""Threshold-encoded gradient sharing across OS processes.

Mirrors the reference's gradient-sharing regime
(EncodedGradientsAccumulator over Aeron): each worker quantizes its update
to a sparse ±threshold encoding with residual error feedback, a hub
exchanges and averages the encodings, and every worker applies the same
decoded mean — so worker parameters stay bit-identical without sending
dense gradients. Here two REAL processes train a least-squares model
through the socket hub. Run:
python examples/distributed_gradient_sharing.py [--smoke]
"""

import subprocess
import sys
import tempfile
import textwrap

import numpy as np

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.parallel.transport import GradientExchangeServer

STEPS = 60 if args.smoke else 400

WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    from deeplearning4j_tpu.parallel.transport import (
        DistributedGradientWorker, SocketGradientTransport)

    port, wid, steps, out = (int(sys.argv[1]), int(sys.argv[2]),
                             int(sys.argv[3]), sys.argv[4])
    rng = np.random.default_rng(0)        # same data-generating seed
    X = rng.standard_normal((256, 64)).astype(np.float32)
    w_true = rng.standard_normal(64).astype(np.float32)
    y = X @ w_true
    lo, hi = (0, 128) if wid == 0 else (128, 256)   # disjoint shards
    Xw, yw = X[lo:hi], y[lo:hi]

    w = np.zeros(64, np.float32)
    transport = SocketGradientTransport(("127.0.0.1", port))
    worker = DistributedGradientWorker(64, transport, threshold=1e-3)
    for step in range(steps):
        grad = 2 * Xw.T @ (Xw @ w - yw) / len(yw)
        w -= worker.step((0.02 * grad).astype(np.float32))
    transport.close()
    np.save(out, w)
""")

import pathlib

repo = str(pathlib.Path(__file__).resolve().parent.parent)
server = GradientExchangeServer(n_workers=2).start()
port = server.address[1]

with tempfile.TemporaryDirectory() as td:
    procs, outs = [], []
    for wid in range(2):
        out = f"{td}/w{wid}.npy"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER.format(repo=repo),
             str(port), str(wid), str(STEPS), out]))
    for p in procs:
        assert p.wait(timeout=600) == 0
    server.stop()
    w0, w1 = (np.load(o) for o in outs)

print(f"hub exchanged {server.rounds} rounds")
assert (w0 == w1).all(), "workers diverged!"
print("worker parameters bit-identical:", w0[:4], "...")
print("OK")
