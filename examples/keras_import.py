"""Import a Keras .h5 model and verify identical outputs.

Mirrors the reference's Keras import examples (KerasModelImport): save a
compiled Keras Sequential model, import it, compare predictions, then
fine-tune the imported network natively. Requires tensorflow (CPU) for
the save step only. Run: python examples/keras_import.py [--smoke]
"""

import tempfile

import numpy as np

from _common import setup

args = setup(__doc__)

try:
    import tensorflow as tf
except ImportError:
    print("SKIP: tensorflow not installed (needed only to produce the .h5)")
    raise SystemExit(0)

keras = tf.keras
m = keras.Sequential([
    keras.layers.Input((20,)),
    keras.layers.Dense(32, activation="relu"),
    keras.layers.Dropout(0.2),
    keras.layers.Dense(5, activation="softmax"),
])
# compiling records the loss in the .h5 — the importer then converts the
# softmax head into a trainable OutputLayer (uncompiled models import for
# inference only)
m.compile(loss="categorical_crossentropy", optimizer="adam")
x = np.random.default_rng(0).random((8, 20)).astype(np.float32)
want = m.predict(x, verbose=0)

with tempfile.NamedTemporaryFile(suffix=".h5") as f:
    m.save(f.name)
    from deeplearning4j_tpu.import_.keras import import_keras_sequential
    net = import_keras_sequential(f.name)

got = np.asarray(net.output(x))
np.testing.assert_allclose(got, want, atol=1e-5)
print("imported model matches keras predictions (atol 1e-5)")

# the imported network is a first-class MultiLayerNetwork: fine-tune it
from deeplearning4j_tpu.data import ListDataSetIterator
from deeplearning4j_tpu.data.dataset import DataSet

y = np.eye(5, dtype=np.float32)[np.random.default_rng(1).integers(0, 5, 64)]
xf = np.random.default_rng(2).random((64, 20)).astype(np.float32)
net.fit(ListDataSetIterator([DataSet(xf[i:i + 16], y[i:i + 16])
                             for i in range(0, 64, 16)]))
print("OK — imported net fine-tunes natively")
