"""Hyperparameter search with Arbiter: random + genetic candidates.

Mirrors the reference's Arbiter examples: declare parameter spaces, give
the runner a train-and-score closure, let it hunt. Run:
python examples/hyperparameter_search.py [--smoke]
"""

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                        GeneticSearchCandidateGenerator,
                                        IntegerParameterSpace,
                                        OptimizationRunner)
from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train import Adam

space = {
    "lr": ContinuousParameterSpace(1e-4, 1e-2, log_scale=True),
    "hidden": IntegerParameterSpace(16, 128),
}

n = 1024 if args.smoke else 4096
train = MnistDataSetIterator(batch_size=128, flatten=True, train=True,
                             num_examples=n, seed=1)
test = MnistDataSetIterator(batch_size=128, flatten=True, train=False,
                            num_examples=512, seed=1)


def score_fn(candidate):
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(candidate["lr"])).list()
            .layer(DenseLayer(n_in=784, n_out=candidate["hidden"],
                              activation="relu"))
            .layer(OutputLayer(n_in=candidate["hidden"], n_out=10,
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init((784,))
    net.fit(train, epochs=1)
    train.reset()
    acc = net.evaluate(test).accuracy()
    test.reset()
    return acc


gen = GeneticSearchCandidateGenerator(
    space, population_size=4,
    max_candidates=6 if args.smoke else 24, seed=9)
runner = OptimizationRunner(gen, score_fn, minimize=False)
best = runner.execute()
print(f"tried {len(runner.results)} candidates; "
      f"best acc={best.score:.4f} with {best.candidate}")
assert best.score > 0.5
print("OK")
