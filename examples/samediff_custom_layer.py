"""A custom layer defined with SameDiff ops inside a standard network.

Mirrors the reference's SameDiff custom-layer example
(org.deeplearning4j.nn.conf.layers.samediff.SameDiffLayer): define
parameters + the forward graph declaratively; autodiff provides the
backward. Run: python examples/samediff_custom_layer.py [--smoke]
"""

from dataclasses import dataclass

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   SameDiffLayer, SDLayerParams)
from deeplearning4j_tpu.train import Adam


@dataclass
class GatedDense(SameDiffLayer):
    """y = sigmoid(x @ Wg) * tanh(x @ Wv) — a little GLU block."""

    n_in: int = 784
    n_out: int = 64

    def define_parameters(self, p: SDLayerParams):
        p.add_weight_param("Wg", self.n_in, self.n_out)
        p.add_weight_param("Wv", self.n_in, self.n_out)

    def define_layer(self, sd, x, params, mask=None):
        gate = sd.nn.sigmoid(x.mmul(params["Wg"]))
        value = sd.math.tanh(x.mmul(params["Wv"]))
        return gate * value


conf = (NeuralNetConfiguration.builder()
        .seed(3).updater(Adam(1e-3)).list()
        .layer(GatedDense(n_in=784, n_out=64))
        .layer(OutputLayer(n_in=64, n_out=10, activation="softmax"))
        .build())
net = MultiLayerNetwork(conf)
net.init((784,))
n = 2048 if args.smoke else 4096
net.fit(MnistDataSetIterator(batch_size=128, flatten=True, train=True, num_examples=n,
                             seed=3), epochs=3)
ev = net.evaluate(MnistDataSetIterator(batch_size=128, flatten=True, train=False,
                                       num_examples=512, seed=3))
print(ev.stats())
assert ev.accuracy() > 0.6, ev.accuracy()
print(f"OK accuracy={ev.accuracy():.4f}")
