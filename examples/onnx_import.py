"""Import an ONNX model (exported from PyTorch) and verify identical outputs.

Mirrors the reference's ONNX import path: export a torch model to ONNX
bytes, import into a SameDiff graph, compare predictions. Requires torch
(CPU) for the export step only. Run: python examples/onnx_import.py
[--smoke]
"""

import io

import numpy as np

from _common import setup

args = setup(__doc__)

try:
    import torch
except ImportError:
    print("SKIP: torch not installed (needed only to produce the .onnx)")
    raise SystemExit(0)

# torch's exporter imports `onnx` only to splice in custom-function protos;
# with none present it returns the bytes unchanged, so an empty stub
# satisfies it on images without the onnx package (our importer parses the
# wire format itself).
import sys as _sys
import types as _types

if "onnx" not in _sys.modules:
    _stub = _types.ModuleType("onnx")

    class _StubGraph:
        node = ()

    class _StubModel:
        graph = _StubGraph()

    _stub.load_model_from_string = lambda b: _StubModel()
    _sys.modules["onnx"] = _stub

from deeplearning4j_tpu.autodiff.onnx_import import import_onnx

model = torch.nn.Sequential(
    torch.nn.Conv2d(1, 8, 3, padding=1), torch.nn.ReLU(),
    torch.nn.MaxPool2d(2),
    torch.nn.Flatten(),
    torch.nn.Linear(8 * 14 * 14, 10), torch.nn.Softmax(dim=-1))
model.eval()
x = torch.randn(4, 1, 28, 28)

buf = io.BytesIO()
torch.onnx.export(model, (x,), buf, opset_version=13, dynamo=False,
                  input_names=["input"], output_names=["out"])

sd, outs = import_onnx(buf.getvalue())
got = np.asarray(outs[0].eval({"input": x.numpy()}))
want = model(x).detach().numpy()
np.testing.assert_allclose(got, want, atol=1e-4)
print(f"imported conv net matches torch (max |diff| = "
      f"{np.abs(got - want).max():.2e})")
print("OK")
