"""MNIST single-layer MLP — the "hello world".

Mirrors the reference's MLPMnistSingleLayerExample: one dense hidden
layer + softmax output, trained with fit(DataSetIterator), evaluated with
Evaluation. Run: python examples/mnist_mlp.py [--smoke]
"""

from _common import setup

args = setup(__doc__)

from deeplearning4j_tpu.data import MnistDataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train import Adam

n = 2048 if args.smoke else 8192
epochs = 5 if args.smoke else 5

conf = (NeuralNetConfiguration.builder()
        .seed(123)
        .updater(Adam(1e-3))
        .list()
        .layer(DenseLayer(n_in=784, n_out=256, activation="relu"))
        .layer(OutputLayer(n_in=256, n_out=10, activation="softmax",
                           loss="mcxent"))
        .build())

net = MultiLayerNetwork(conf)
net.init((784,))

train = MnistDataSetIterator(batch_size=128, flatten=True, train=True, num_examples=n,
                             seed=123)
test = MnistDataSetIterator(batch_size=128, flatten=True, train=False,
                            num_examples=max(n // 4, 512), seed=123)

net.fit(train, epochs=epochs)
ev = net.evaluate(test)
print(ev.stats())
assert ev.accuracy() > (0.80 if args.smoke else 0.95), ev.accuracy()
print(f"OK accuracy={ev.accuracy():.4f}")
