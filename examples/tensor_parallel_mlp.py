"""Tensor parallelism on a user-built network (Megatron-style splits).

Column/RowParallelDense declare their weight shardings as PartitionSpecs;
GSPMD inserts the collectives when ParallelWrapper runs the net on a
dp×tp mesh. The parameter trajectory matches the single-device run
exactly — tensor parallelism here is a LAYOUT declaration, not different
math. Runs on 8 virtual CPU devices; unchanged on a TPU slice. Run:
python examples/tensor_parallel_mlp.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import (DenseLayer, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import (ColumnParallelDense,
                                         ParallelWrapper, RowParallelDense,
                                         make_mesh)
from deeplearning4j_tpu.train import Sgd


def mlp(hidden_cls1, hidden_cls2):
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.05))
            .list()
            .layer(hidden_cls1(n_in=32, n_out=64, activation="relu"))
            .layer(hidden_cls2(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init((32,))


rng = np.random.default_rng(0)
X = rng.random((64, 32), np.float32)
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
ds = DataSet(jnp.asarray(X), jnp.asarray(Y))
steps = 5 if args.smoke else 30

single = mlp(DenseLayer, DenseLayer)
ref_losses = [single.fit(ds) for _ in range(steps)]

tp_net = mlp(ColumnParallelDense, RowParallelDense)
mesh = make_mesh(jax.devices()[:4], dp=2, tp=2)
pw = ParallelWrapper(tp_net, mesh=mesh)
tp_losses = [pw.fit([ds]) for _ in range(steps)]

np.testing.assert_allclose(ref_losses, tp_losses, atol=1e-5)
spec = tp_net.params["layer_0"]["W"].sharding.spec
print(f"layer_0 W sharded as {tuple(spec)} over mesh "
      f"{dict(zip(mesh.axis_names, mesh.devices.shape))}")
print(f"losses match single-device to 1e-5 over {steps} steps")
print("OK")
