"""Ring attention: exact causal attention over a sequence-sharded mesh.

Long-context training shards the SEQUENCE across devices (`sp` axis); the
(T, T) score matrix never exists on any one chip — key/value blocks rotate
around the ring via ppermute while each device holds only its local
T/n_devices slice. This example runs on 8 virtual CPU devices; the same
code runs unchanged on a TPU pod slice. Run:
python examples/long_context_ring_attention.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel import make_mesh, ring_attention

B, T, H, D = (2, 64, 2, 8) if args.smoke else (4, 4096, 8, 64)
mesh = make_mesh(dp=2, sp=4)

rng = np.random.default_rng(0)
q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
           for _ in range(3))

out = ring_attention(mesh, q, k, v, causal=True)
print("ring attention out:", out.shape, "on mesh", dict(
    zip(mesh.axis_names, mesh.devices.shape)))

# exactness vs the single-device reference
ref = jax.nn.dot_product_attention(q, k, v, is_causal=True)
err = float(jnp.abs(out - ref).max())
print(f"max |ring - reference| = {err:.2e}")
assert err < 2e-5
print("OK — exact attention, sequence sharded 4-way")
