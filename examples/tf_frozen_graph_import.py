"""Import a TensorFlow frozen graph and verify identical outputs.

Mirrors the reference's TFGraphMapper path: build a small conv graph with
TF1-compat ops (conv + fused batch norm + pool + softmax — the frozen-
inference idiom), import the GraphDef into SameDiff, compare against a
TF session run. Requires tensorflow (CPU) for the graph build only. Run:
python examples/tf_frozen_graph_import.py [--smoke]
"""

import numpy as np

from _common import setup

args = setup(__doc__)

try:
    import tensorflow as tf
except ImportError:
    print("SKIP: tensorflow not installed (needed only to build the graph)")
    raise SystemExit(0)

tf1 = tf.compat.v1
rng = np.random.default_rng(0)

g = tf1.Graph()
with g.as_default():
    x = tf1.placeholder(tf.float32, (None, 8, 8, 3), name="x")
    k = tf1.constant(rng.standard_normal((3, 3, 3, 4)).astype(np.float32)
                     * 0.3)
    conv = tf1.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
    gamma = tf1.constant(rng.uniform(0.5, 1.5, 4).astype(np.float32))
    beta = tf1.constant(rng.standard_normal(4).astype(np.float32))
    mean = tf1.constant(rng.standard_normal(4).astype(np.float32))
    var = tf1.constant(rng.uniform(0.5, 2.0, 4).astype(np.float32))
    bn, _, _ = tf1.nn.fused_batch_norm(conv, gamma, beta, mean, var,
                                       is_training=False)
    act = tf.nn.relu(bn)
    pool = tf1.nn.max_pool2d(act, ksize=2, strides=2, padding="VALID")
    flat = tf1.reshape(pool, (-1, 4 * 4 * 4))
    w = tf1.constant(rng.standard_normal((64, 5)).astype(np.float32) * 0.2)
    tf.nn.softmax(tf1.matmul(flat, w), name="out")

from deeplearning4j_tpu.autodiff.tf_import import import_frozen_graph

sd, _ = import_frozen_graph(g.as_graph_def())
feats = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
got = np.asarray(sd.eval(sd.get_variable("out"), {"x": feats}))
with tf1.Session(graph=g) as sess:
    want = sess.run("out:0", {"x:0": feats})
np.testing.assert_allclose(got, want, atol=1e-5)
print(f"imported frozen graph matches TF session "
      f"(max |diff| = {np.abs(got - want).max():.2e})")
print("OK")
